"""Federated analytics operators.

Parity with the reference analyzer set (``fa/local_analyzer/*`` +
``fa/aggregator/*``, SURVEY.md §2.15): average, frequency estimation,
heavy hitter (TrieHH — DP trie growth), set intersection, union,
k-percentile.  Host-side numpy: analytics payloads are tiny; the federation
structure (sampling, rounds, per-client locality), not FLOPs, is the point.
"""

from __future__ import annotations

from collections import Counter, defaultdict
from typing import Any, Optional

import numpy as np

from .frame import FAClientAnalyzer, FAServerAggregator


# ---------------------------------------------------------------------------
# average (fa/local_analyzer/avg.py)
# ---------------------------------------------------------------------------

class AvgClientAnalyzer(FAClientAnalyzer):
    def local_analyze(self, data, cfg):
        return (float(np.sum(data)), int(np.size(data)))


class AvgServerAggregator(FAServerAggregator):
    def __init__(self, cfg=None):
        super().__init__(cfg)
        self.total, self.count = 0.0, 0

    def aggregate(self, submissions):
        for s, c in submissions:
            self.total += s
            self.count += c
        self.server_data = self.total / max(self.count, 1)
        return self.server_data


# ---------------------------------------------------------------------------
# frequency estimation (fa/local_analyzer/frequency_estimation.py)
# ---------------------------------------------------------------------------

class FrequencyClientAnalyzer(FAClientAnalyzer):
    def local_analyze(self, data, cfg):
        vals, counts = np.unique(np.asarray(data), return_counts=True)
        return dict(zip(vals.tolist(), counts.tolist()))


class FrequencyServerAggregator(FAServerAggregator):
    def __init__(self, cfg=None):
        super().__init__(cfg)
        self.freq: Counter = Counter()

    def aggregate(self, submissions):
        for sub in submissions:
            self.freq.update(sub)
        total = sum(self.freq.values())
        self.server_data = {k: v / total for k, v in self.freq.items()}
        return self.server_data


# ---------------------------------------------------------------------------
# heavy hitters — TrieHH (fa/local_analyzer/heavy_hitter_triehh.py)
# ---------------------------------------------------------------------------

class TrieHHClientAnalyzer(FAClientAnalyzer):
    """Each round, a client votes for the (prefix + next char) extension of
    its word if the prefix is already in the server trie."""

    def local_analyze(self, data, cfg):
        import zlib

        trie = self.init_msg or {""}
        votes = Counter()
        words = [str(w) for w in np.ravel(data)]
        # deterministic but ROUND-VARYING word sample: seeded by the client's
        # data and a per-analyzer round counter — a fixed per-client seed
        # would vote the same word forever and starve every other heavy
        # hitter (hash() itself is salted per interpreter; the trie state is
        # no good as a seed either, since it stops changing once saturated)
        self._round_no = getattr(self, "_round_no", -1) + 1
        seed_src = "|".join(words[:4]) + f"#r{self._round_no}"
        rng = np.random.RandomState(zlib.crc32(seed_src.encode()) % (2**31))
        if not words:
            return votes
        w = words[rng.randint(len(words))]  # one word per client per round (DP)
        for L in range(1, len(w) + 1):
            if w[: L - 1] in trie:
                votes[w[:L]] += 1
        return votes


class TrieHHServerAggregator(FAServerAggregator):
    """Grow the trie with extensions voted >= theta times (DP threshold)."""

    def __init__(self, cfg=None, theta: int = 2, max_len: int = 10):
        super().__init__(cfg)
        self.theta = theta
        self.max_len = max_len
        self.trie: set = {""}

    def init_msg(self):
        return set(self.trie)

    def aggregate(self, submissions):
        votes: Counter = Counter()
        for sub in submissions:
            votes.update(sub)
        for prefix, c in votes.items():
            if c >= self.theta and len(prefix) <= self.max_len:
                self.trie.add(prefix)
        self.server_data = self.trie
        return self.trie

    def heavy_hitters(self) -> set:
        """Maximal trie entries (complete voted words/prefixes)."""
        return {p for p in self.trie if p and not any(
            q != p and q.startswith(p) for q in self.trie
        )}


# ---------------------------------------------------------------------------
# intersection / union (fa/local_analyzer/intersection.py, union.py)
# ---------------------------------------------------------------------------

class IntersectionClientAnalyzer(FAClientAnalyzer):
    def local_analyze(self, data, cfg):
        return set(np.unique(np.asarray(data)).tolist())


class IntersectionServerAggregator(FAServerAggregator):
    def aggregate(self, submissions):
        for s in submissions:
            self.server_data = set(s) if self.server_data is None else self.server_data & set(s)
        return self.server_data


class UnionServerAggregator(FAServerAggregator):
    def aggregate(self, submissions):
        for s in submissions:
            self.server_data = set(s) if self.server_data is None else self.server_data | set(s)
        return self.server_data


# ---------------------------------------------------------------------------
# k-percentile (fa/local_analyzer/k_percentile.py) — distributed quantile by
# iterative bisection on candidate values (clients only report counts)
# ---------------------------------------------------------------------------

class KPercentileClientAnalyzer(FAClientAnalyzer):
    def local_analyze(self, data, cfg):
        pivot = self.init_msg
        arr = np.asarray(data, dtype=np.float64)
        return (int(np.sum(arr <= pivot)), int(arr.size), float(arr.min()), float(arr.max()))


class KPercentileServerAggregator(FAServerAggregator):
    def __init__(self, cfg=None, k: float = 50.0, iters_done_eps: float = 1e-6):
        super().__init__(cfg)
        self.k = k
        self.lo: Optional[float] = None
        self.hi: Optional[float] = None
        self.pivot: float = 0.0
        self.eps = iters_done_eps

    def init_msg(self):
        if self.lo is None:
            return self.pivot
        self.pivot = 0.5 * (self.lo + self.hi)
        return self.pivot

    def aggregate(self, submissions):
        below = sum(s[0] for s in submissions)
        total = sum(s[1] for s in submissions)
        lo = min(s[2] for s in submissions)
        hi = max(s[3] for s in submissions)
        if self.lo is None:
            self.lo, self.hi = lo, hi
            self.pivot = 0.5 * (lo + hi)
            return self.pivot
        frac = 100.0 * below / max(total, 1)
        if frac < self.k:
            self.lo = self.pivot
        else:
            self.hi = self.pivot
        self.server_data = 0.5 * (self.lo + self.hi)
        return self.server_data


_ANALYZERS = {
    "avg": (AvgClientAnalyzer, AvgServerAggregator),
    "frequency_estimation": (FrequencyClientAnalyzer, FrequencyServerAggregator),
    "heavy_hitter_triehh": (TrieHHClientAnalyzer, TrieHHServerAggregator),
    "intersection": (IntersectionClientAnalyzer, IntersectionServerAggregator),
    "union": (IntersectionClientAnalyzer, UnionServerAggregator),
    "k_percentile": (KPercentileClientAnalyzer, KPercentileServerAggregator),
}


def create_analyzer_pair(task: str, cfg=None):
    """Reference ``fa`` dispatch on the analytics task name."""
    try:
        ca, sa = _ANALYZERS[task]
    except KeyError:
        raise ValueError(f"unknown FA task {task!r}; known: {sorted(_ANALYZERS)}") from None
    return ca(cfg), sa(cfg)
