"""Loss and metric functions (pure, shape-polymorphic over task families).

Replaces the reference's per-task trainer branches (CE for classification
``my_model_trainer_classification.py``, NWP/seq CE, MSE regression in
``my_model_trainer_regression.py``, BCE for tag prediction) with one dispatch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean CE.  Handles (B, C) + int (B,) and seq (B, T, C) + (B, T)."""
    if logits.ndim == labels.ndim + 1:
        return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
    # multi-hot targets (stackoverflow_lr tag prediction)
    return optax.sigmoid_binary_cross_entropy(logits, labels).mean()


def mse(pred: jax.Array, target: jax.Array) -> jax.Array:
    return jnp.mean((pred - target) ** 2)


def accuracy_count(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Number of correct predictions (summable across shards/batches)."""
    pred = jnp.argmax(logits, axis=-1)
    return jnp.sum(pred == labels)


def get_loss_fn(name: str):
    if name == "cross_entropy":
        return cross_entropy
    if name == "mse":
        return mse
    raise ValueError(f"unknown loss {name!r}")
