"""Shared FL types.

Replaces the reference's duck-typed ``args`` threading and the
``Params``/``Context`` kwargs bags (``core/alg_frame/params.py``,
``context.py``) with small typed containers that are jit-friendly
(pytrees of arrays) or static (frozen dataclasses hashed into the trace).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

import jax


@dataclass(frozen=True)
class HParams:
    """Static (trace-time) hyperparameters of the local problem.

    One frozen dataclass instead of ``hasattr`` probing on ``args``
    (reference ``ml/trainer/my_model_trainer_classification.py:21-60``).
    """

    epochs: int = 1
    batch_size: int = 32
    learning_rate: float = 0.03
    momentum: float = 0.0
    weight_decay: float = 0.0
    client_optimizer: str = "sgd"
    server_optimizer: str = "sgd"
    server_lr: float = 1.0
    server_momentum: float = 0.0
    # algorithm knobs (see Config for provenance)
    fedprox_mu: float = 0.0
    feddyn_alpha: float = 0.01
    mime_momentum: float = 0.9
    steps_per_epoch: int = 0  # static: ceil(capacity / batch_size)
    step_mode: str = "match"  # match reference per-client step counts | fixed
    compute_dtype: str = "float32"
    loss: str = "cross_entropy"
    # fused Pallas conv epilogues (ops/pallas/fused_block.py); the model
    # factory reads the same flag from cfg extra — carried here so the local
    # step and bench can report which kernel path a recipe ran
    fused_blocks: bool = False

    @property
    def local_steps(self) -> int:
        return self.epochs * self.steps_per_epoch


class ClientOutput:
    """What a client sends up: its contribution (pytree — full weights for
    FedAvg-family, grads for FedSGD, tuples for SCAFFOLD), refreshed persistent
    client state, and local metrics.  Registered as a pytree so it can flow
    through vmap/scan."""

    def __init__(self, contribution: Any, client_state: Any, metrics: dict):
        self.contribution = contribution
        self.client_state = client_state
        self.metrics = metrics

    def tree_flatten(self):
        return (self.contribution, self.client_state, self.metrics), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    ClientOutput,
    lambda co: co.tree_flatten(),
    lambda aux, children: ClientOutput.tree_unflatten(aux, children),
)
