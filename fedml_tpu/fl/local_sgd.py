"""Local training as a jitted scan — the TPU form of ``ClientTrainer.train``.

The reference's local loop (``ml/trainer/my_model_trainer_classification.py:21``)
is epochs x minibatches of torch fwd/bwd/step on one device.  Here the same
loop is ``lax.scan`` over ``epochs * steps_per_epoch`` steps of an optax
update, so XLA compiles ONE program per round and the whole client dimension
vmaps/shards over the mesh (SURVEY.md §3.1 "hot loops -> jit(scan)").

Ragged client shards (SURVEY.md §7 hard part 1) are handled by:
- cyclic-padded shards (every slot is a real sample, see ``data.dataset``),
- per-epoch permutations for shuffled epoch semantics,
- ``step_mode="match"``: steps beyond a client's own budget
  ``epochs * ceil(count/batch)`` are masked to no-ops, reproducing the
  reference's per-client step counts while keeping shapes static.

Algorithm customisation is via two pure hooks (closed over at build time):
``loss_extra(params, global_params, ctx)`` (FedProx/FedDyn terms) and
``grad_hook(grads, ctx)`` (SCAFFOLD/Mime corrections).

With ``hp.fused_blocks`` recipes the model's conv epilogues run through the
fused Pallas kernel (``ops/pallas/fused_block.py``), whose ``custom_vjp``
saves the conv output + activation as backward residuals.  Those residuals
are INTRA-step: ``value_and_grad`` consumes them inside one ``step`` body, so
they never enter the scan carry and are dead by the time the carry is
donated — the fused path composes with ``jit(scan)`` + donation unchanged
(the parity tests and the MeshSimulator fused smoke test pin this down).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax

from ..core import pytree as pt
from .losses import get_loss_fn
from .types import HParams


def make_optimizer(hp: HParams) -> optax.GradientTransformation:
    if hp.client_optimizer == "sgd":
        chain = []
        if hp.weight_decay:
            chain.append(optax.add_decayed_weights(hp.weight_decay))
        chain.append(optax.sgd(hp.learning_rate, momentum=hp.momentum or None))
        return optax.chain(*chain)
    if hp.client_optimizer == "adam":
        return optax.adamw(hp.learning_rate, weight_decay=hp.weight_decay)
    raise ValueError(f"unknown client optimizer {hp.client_optimizer!r}")


def split_variables(variables: dict) -> tuple[Any, dict]:
    """Split flax variables into (params, rest-collections e.g. batch_stats)."""
    params = variables["params"]
    rest = {k: v for k, v in variables.items() if k != "params"}
    return params, rest


def make_local_train_fn(
    model,
    hp: HParams,
    loss_extra: Optional[Callable] = None,
    grad_hook: Optional[Callable] = None,
    batch_constraint: Optional[Callable] = None,
):
    """Build ``local_train(variables, x, y, count, key, ctx) -> (new_variables, metrics)``.

    ``ctx`` is an arbitrary pytree threaded to the hooks (global params,
    control variates, server momentum...).  All shapes static; jit/vmap-safe.

    ``batch_constraint(bx, by) -> (bx, by)`` is applied to each step's
    gathered minibatch — the intra-silo data-parallel hook: constraining the
    batch dim to a device axis makes GSPMD partition the fwd/bwd compute and
    insert the gradient all-reduce (without it, sharding only the at-rest
    arrays gets re-assembled by the random-index gather and the compute
    replicates).
    """
    if hp.steps_per_epoch <= 0:
        raise ValueError(
            "HParams.steps_per_epoch must be positive (got "
            f"{hp.steps_per_epoch}); build it via algorithms.hparams_from_config"
            "(cfg, steps_per_epoch=ceil(capacity/batch)) or the simulator, which"
            " computes it from the stacked client capacity"
        )
    base_loss = get_loss_fn(hp.loss)
    opt = make_optimizer(hp)
    compute_dtype = jnp.bfloat16 if hp.compute_dtype == "bfloat16" else jnp.float32

    def loss_fn(params, rest, x, y, dropout_key, ctx):
        variables = {"params": params, **rest}
        mutable = [k for k in rest.keys()]
        x = x.astype(compute_dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x
        if mutable:
            logits, new_rest = model.apply(
                variables, x, train=True, mutable=mutable, rngs={"dropout": dropout_key}
            )
        else:
            logits = model.apply(variables, x, train=True, rngs={"dropout": dropout_key})
            new_rest = rest
        loss = base_loss(logits.astype(jnp.float32), y)
        if loss_extra is not None:
            loss = loss + loss_extra(params, ctx)
        return loss, new_rest

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def local_train(variables: dict, x: jax.Array, y: jax.Array, count: jax.Array, key: jax.Array, ctx=None):
        params, rest = split_variables(variables)
        if x.shape[0] < hp.batch_size:
            # the old per-epoch dynamic_slice rejected this at trace time
            # (slice size > dim); keep the refusal explicit
            raise ValueError(
                f"client shard capacity {x.shape[0]} is smaller than "
                f"batch_size {hp.batch_size}; pad the shard (stack_clients "
                "with multiple_of=batch_size) or lower the batch size"
            )
        opt_state = opt.init(params)
        # A stateless optimizer (plain SGD: no momentum/adam moments) lets
        # step_mode=match masking ride a multiply on the updates instead of a
        # 2-tree select: u*active is bit-identical to the select for a 0/1
        # mask and fuses into the same FMA pass as apply_updates.
        stateless_opt = not jax.tree_util.tree_leaves(opt_state)
        cap = x.shape[0]
        bsz = hp.batch_size
        spe = hp.steps_per_epoch
        total_steps = hp.epochs * spe
        # per-client step budget (reference: epochs * ceil(len(local)/batch))
        own_steps = hp.epochs * ((count + bsz - 1) // bsz)

        # Per-epoch permutations hoisted OUT of the step scan: the permutation
        # is constant within an epoch, but recomputing it per step costs a
        # cap-sized sort per client per step (sorts are multi-pass on TPU and
        # showed up as real round time in scripts/profile_fedavg.py).  The
        # flattened (epochs*cap,) table holds epoch e's permutation at offset
        # e*cap, so each step slices its batch at epoch*cap + step*bsz.
        all_perms = jax.vmap(
            lambda e: jax.random.permutation(
                jax.random.fold_in(jax.random.fold_in(key, e), 1), cap
            )
        )(jnp.arange(hp.epochs)).reshape(-1)

        def step(carry, s):
            params, rest, opt_state = carry
            epoch = s // spe
            step_in_epoch = s % spe
            ekey = jax.random.fold_in(key, epoch)
            # clamp the slice start inside the epoch's own block — the old
            # per-epoch dynamic_slice clamped at cap-bsz, and when cap is not
            # a batch multiple an unclamped flat offset would read into the
            # NEXT epoch's permutation (cap >= bsz is asserted above)
            start = jnp.minimum(step_in_epoch * bsz, cap - bsz)
            idx = jax.lax.dynamic_slice_in_dim(all_perms, epoch * cap + start, bsz)
            bx = jnp.take(x, idx, axis=0)
            by = jnp.take(y, idx, axis=0)
            if batch_constraint is not None:
                bx, by = batch_constraint(bx, by)
            dkey = jax.random.fold_in(ekey, 2 + step_in_epoch)
            (loss, new_rest), grads = grad_fn(params, rest, bx, by, dkey, ctx)
            if grad_hook is not None:
                grads = grad_hook(grads, ctx)
            updates, new_opt = opt.update(grads, opt_state, params)
            if hp.step_mode == "match":
                active = s < own_steps
                if stateless_opt:
                    # where(), not u*active: inf/NaN updates on inactive steps
                    # would turn 0*inf into NaN and corrupt the frozen params
                    updates = jax.tree_util.tree_map(
                        lambda u: jnp.where(active, u, jnp.zeros_like(u)), updates
                    )
                    new_params = optax.apply_updates(params, updates)
                else:
                    new_params = optax.apply_updates(params, updates)
                    new_params = _select_tree(active, new_params, params)
                    new_opt = _select_tree(active, new_opt, opt_state)
                new_rest = _select_tree(active, new_rest, rest)
                loss = jnp.where(active, loss, 0.0)
                active_f = active.astype(jnp.float32)
            else:
                new_params = optax.apply_updates(params, updates)
                active_f = jnp.float32(1.0)
            return (new_params, new_rest, new_opt), (loss, active_f)

        (params, rest, _), (losses, actives) = jax.lax.scan(
            step, (params, rest, opt_state), jnp.arange(total_steps)
        )
        n_active = jnp.maximum(jnp.sum(actives), 1.0)
        metrics = {
            "train_loss": jnp.sum(losses) / n_active,
            "num_steps": n_active,
            "num_samples": count.astype(jnp.float32),
        }
        return {"params": params, **rest}, metrics

    return local_train


def _select_tree(pred, on_true, on_false):
    return jax.tree_util.tree_map(lambda t, f: jnp.where(pred, t, f), on_true, on_false)


def make_full_grad_fn(model, hp: HParams):
    """Gradient of the mean loss over a client's whole (cyclic-padded) shard,
    at fixed variables — the FedSGD client step and Mime's ``grad f_i(x)``.
    Batched scan; batch_stats frozen (inference statistics)."""
    base_loss = get_loss_fn(hp.loss)
    bsz = hp.batch_size

    def full_grad(variables: dict, x: jax.Array, y: jax.Array, count: jax.Array, key: jax.Array):
        params, rest = split_variables(variables)
        cap = x.shape[0]
        n_batches = cap // bsz

        def loss_of(params, bx, by, dkey):
            if rest:
                logits, _ = model.apply(
                    {"params": params, **rest}, bx, train=True,
                    mutable=list(rest.keys()), rngs={"dropout": dkey},
                )
            else:
                logits = model.apply({"params": params}, bx, train=True, rngs={"dropout": dkey})
            return base_loss(logits.astype(jnp.float32), by)

        gfn = jax.grad(loss_of)

        def body(acc, i):
            bx = jax.lax.dynamic_slice_in_dim(x, i * bsz, bsz)
            by = jax.lax.dynamic_slice_in_dim(y, i * bsz, bsz)
            g = gfn(params, bx, by, jax.random.fold_in(key, i))
            return jax.tree_util.tree_map(jnp.add, acc, g), None

        zero = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, jnp.float32), params)
        acc, _ = jax.lax.scan(body, zero, jnp.arange(n_batches))
        return jax.tree_util.tree_map(lambda g: g / jnp.maximum(n_batches, 1), acc)

    return full_grad


def make_eval_fn(model, hp: HParams, batch_size: int = 256):
    """Global test eval: batched scan over a (padded) test set with a
    validity mask; returns (loss, accuracy) — the TPU form of
    ``ServerAggregator.test`` (``ml/aggregator/default_aggregator.py``)."""
    base_loss = get_loss_fn(hp.loss)

    def eval_fn(variables: dict, x: jax.Array, y: jax.Array, n_valid: jax.Array):
        n = x.shape[0]
        n_batches = n // batch_size

        def body(carry, i):
            loss_sum, correct, seen = carry
            bx = jax.lax.dynamic_slice_in_dim(x, i * batch_size, batch_size)
            by = jax.lax.dynamic_slice_in_dim(y, i * batch_size, batch_size)
            pos = i * batch_size + jnp.arange(batch_size)
            mask = (pos < n_valid).astype(jnp.float32)
            logits = model.apply(variables, bx, train=False)
            logits = logits.astype(jnp.float32)
            if logits.ndim == by.ndim + 1:
                per = optax.softmax_cross_entropy_with_integer_labels(logits, by)
                pred_ok = (jnp.argmax(logits, -1) == by).astype(jnp.float32)
                if per.ndim == 2:  # sequence task: mean over time
                    per = per.mean(-1)
                    pred_ok = pred_ok.mean(-1)
            else:
                per = optax.sigmoid_binary_cross_entropy(logits, by).mean(-1)
                pred_ok = ((logits > 0) == (by > 0.5)).astype(jnp.float32).mean(-1)
            return (
                loss_sum + jnp.sum(per * mask),
                correct + jnp.sum(pred_ok * mask),
                seen + jnp.sum(mask),
            ), None

        (loss_sum, correct, seen), _ = jax.lax.scan(
            body, (jnp.float32(0), jnp.float32(0), jnp.float32(0)), jnp.arange(n_batches)
        )
        seen = jnp.maximum(seen, 1.0)
        return {"test_loss": loss_sum / seen, "test_acc": correct / seen}

    return eval_fn
