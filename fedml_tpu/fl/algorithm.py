"""FedAlgorithm — the pure-function frame replacing ClientTrainer/ServerAggregator.

The reference couples algorithm logic to its actor runtime: ``ClientTrainer``
(``core/alg_frame/client_trainer.py:10``) mutates a model in-place on a worker
process, and ``ServerAggregator`` + ``FedMLAggOperator.agg``
(``core/alg_frame/server_aggregator.py:14``, ``ml/aggregator/agg_operator.py:9``)
branch per optimizer on lists of state_dicts.  Here an algorithm is five pure
methods over pytrees — everything composes with jit/vmap/shard_map and runs
identically on the sequential SP backend and the sharded MESH backend:

- ``init_server_state``  (server optimizer state, control variates, momentum)
- ``init_client_state``  (per-client persistent state; stacked over clients)
- ``client_update``      (local training -> ClientOutput.contribution)
- ``aggregate``          (stacked contributions + weights -> aggregate)
- ``server_update``      (aggregate -> new global variables)

Defaults implement FedAvg: sample-weighted mean of full client weights
(the exact math of ``fedavg_api.py:144-159`` / ``agg_operator.py`` "FedAvg"
branch) and identity server step.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax

from ..core import pytree as pt
from .local_sgd import make_local_train_fn, split_variables
from .types import ClientOutput, HParams


class FedAlgorithm:
    name = "FedAvg"

    def __init__(self, hp: HParams, cfg=None):
        self.hp = hp
        self.cfg = cfg
        self._local_train = None

    # -- build ---------------------------------------------------------------
    def build(self, model) -> "FedAlgorithm":
        """Close over the model to build the jit-able local train fn."""
        self._local_train = make_local_train_fn(
            model, self.hp, loss_extra=self.loss_extra(), grad_hook=self.grad_hook()
        )
        return self

    def loss_extra(self):
        return None

    def grad_hook(self):
        return None

    # -- state ---------------------------------------------------------------
    def init_server_state(self, variables: dict) -> Any:
        return ()

    def init_client_state(self, variables: dict) -> Optional[Any]:
        return None

    # -- client side -----------------------------------------------------------
    def make_ctx(self, global_variables: dict, client_state, server_state):
        """Context pytree passed to loss/grad hooks during local training."""
        return None

    def client_update(self, global_variables, client_state, server_state, x, y, count, key) -> ClientOutput:
        ctx = self.make_ctx(global_variables, client_state, server_state)
        new_vars, metrics = self._local_train(global_variables, x, y, count, key, ctx)
        return ClientOutput(contribution=new_vars, client_state=client_state, metrics=metrics)

    # -- server side -----------------------------------------------------------
    def supports_associative_fold(self) -> bool:
        """True when ``aggregate`` is a weight-associative fold: the result
        of ``aggregate(stacked, weights)`` equals folding one ``(update,
        weight)`` at a time into a running weighted sum and dividing at the
        end, in any arrival order.  The stock sample-weighted mean is; this
        is the capability gate for the cross-silo streaming accumulator and
        the buffered-async server (``FedMLAggregator.fold``), which would
        silently compute the wrong thing for an order- or set-sensitive
        ``aggregate`` (trimmed means, coordinate medians, Krum...).  The
        SAME declaration gates the secure-aggregation protocols (ISSUE 15):
        pairwise-mask SecAgg is a mod-field SUM — associative by
        construction — so masked uploads ride a field-domain sibling of the
        f32 fold (``parallel.stream_fold.FieldStreamAccumulator``), and an
        algorithm that cannot fold cannot be secure-aggregated either.  A
        subclass that overrides ``aggregate`` with another associative form
        may opt back in by overriding this to True."""
        return type(self).aggregate is FedAlgorithm.aggregate

    def aggregate(self, stacked_contributions, weights: jax.Array):
        return pt.tree_weighted_mean(stacked_contributions, weights)

    def server_update(self, global_variables, server_state, agg, round_idx):
        return agg, server_state


def config_supports_associative_fold(cfg) -> bool:
    """Whether ``cfg``'s algorithm declares its aggregate weight-associative
    — the config-level form of :meth:`FedAlgorithm.supports_associative_
    fold`, used by the secure-aggregation gates (``cross_silo/secagg_*``)
    before any model exists."""
    from ..algorithms import create as create_algorithm, hparams_from_config

    algo = create_algorithm(cfg, hparams_from_config(cfg, steps_per_epoch=1))
    return bool(algo.supports_associative_fold())


def make_server_optimizer(hp: HParams) -> optax.GradientTransformation:
    """Server-side optimizer for the FedOpt family (reference
    ``sp/fedopt/optrepo.py`` torch-optimizer lookup)."""
    if hp.server_optimizer == "sgd":
        return optax.sgd(hp.server_lr, momentum=hp.server_momentum or None)
    if hp.server_optimizer == "adam":
        return optax.adam(hp.server_lr, b1=0.9, b2=0.99, eps=1e-3)
    if hp.server_optimizer == "adagrad":
        return optax.adagrad(hp.server_lr)
    if hp.server_optimizer == "yogi":
        # FedYogi (Reddi et al.) — adaptive server optimizer
        return optax.yogi(hp.server_lr)
    raise ValueError(f"unknown server optimizer {hp.server_optimizer!r}")
