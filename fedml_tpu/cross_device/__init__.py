"""Cross-device platform ("BeeHive" in the reference) — the server side of
phone-fleet FL.

Parity with ``cross_device/server_mnn/fedml_server_manager.py:14``: a Python
server drives NON-Python device clients.  The reference serializes the
global model to MNN files (``write_tensor_dict_to_mnn``) and talks MQTT to
Android's C++ MobileNN trainer; the TPU build's devices speak the pytree
wire format over the TCP transport, and the reference's C++ trainer role is
filled by ``native/fedml_client.cpp`` (proven in CI by
tests/test_native_client.py + tests/test_cross_device.py).

The round protocol is the shared cross-silo one (message_define.py) — the
reference's cross-device server duplicates the cross-silo flow with MNN
serialization bolted on; here one server implementation serves both
platforms and only the transport/client language differ.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Optional

from ..core.flags import cfg_extra
from ..cross_silo import build_aggregator
from ..cross_silo import message_define as md
from ..cross_silo.server import FedMLServerManager


class DeviceRegistry:
    """Device registration + liveness — the fleet-management piece phones
    need that silos don't (the reference's MLOps device manager tracks
    BeeHive device status the same way: register on first status report,
    refresh on every message, stop scheduling silent devices).

    A device is excluded only after FAILING TO ANSWER ``max_missed`` of its
    own consecutive selections — not by wall clock (which marks the fastest
    uploader of a slow round stale) and not by round count (which would
    evict healthy devices the sampler simply didn't pick).  Excluded devices
    keep receiving status probes, so a recovered phone's reply rejoins it
    (exclusion is never a one-way door)."""

    def __init__(self, max_missed: int = 2):
        self.max_missed = int(max_missed)
        self.devices: dict[int, dict] = {}

    def register(self, device_id: int, os_name: str = "") -> None:
        """First status report, or any later participation signal (upload,
        probe answer): the device is alive — clear its missed counter."""
        d = self.devices.setdefault(
            int(device_id), {"os": os_name or "unknown", "registered": time.time(), "missed": 0},
        )
        if os_name:
            d["os"] = os_name
        d["last_seen"] = time.time()
        d["missed"] = 0

    def note_participation(self, device_id: int, round_idx: int = 0) -> None:
        self.register(device_id)

    def note_missed_selection(self, device_id: int) -> None:
        """The device was selected for a round and never uploaded."""
        d = self.devices.get(int(device_id))
        if d is not None:
            d["missed"] = d.get("missed", 0) + 1

    def is_live(self, device_id: int, round_idx: int = 0) -> bool:
        d = self.devices.get(int(device_id))
        if d is None:
            return False
        return d.get("missed", 0) <= self.max_missed

    def live_ids(self, round_idx: int = 0) -> list[int]:
        return sorted(i for i in self.devices if self.is_live(i))

    def status(self, round_idx: int = 0) -> dict[int, dict]:
        return {
            i: {**d, "live": self.is_live(i)} for i, d in self.devices.items()
        }


class ServerMNN(FedMLServerManager):
    """Cross-device server: cross-silo protocol + per-round global-model
    artifact dump (the reference's ``global_model_file_path`` MNN file,
    here the wire format every client language reads) + device
    registration/liveness via :class:`DeviceRegistry`."""

    def __init__(self, cfg, aggregator, backend: Optional[str] = None, logger=None):
        super().__init__(cfg, aggregator, backend=backend, logger=logger)
        # NOTE: global_model_file_path is a typed Config field — the old
        # extra.get read could never see a recipe value (known keys land on
        # the dataclass, not in extra)
        self.global_model_file_path = getattr(cfg, "global_model_file_path", "") or ""
        self.registry = DeviceRegistry(
            max_missed=int(cfg_extra(cfg, "device_max_missed_rounds"))
        )
        self._uploaded_this_round: set[int] = set()

    # -- device lifecycle -----------------------------------------------------
    def handle_message_client_status(self, msg) -> None:
        # registration AND the rejoin path: a probe answer from an excluded
        # device clears its missed counter
        self.registry.register(
            msg.get_sender_id(), str(msg.get(md.MSG_ARG_KEY_CLIENT_OS) or "")
        )
        super().handle_message_client_status(msg)

    def handle_message_receive_model(self, msg) -> None:
        # Attendance credit only for the current round (a stale duplicate
        # can't shield a silent device from its missed-selection strike).
        # Liveness is judged on a recency window: an upload for the current
        # or immediately previous round proves the device alive (late-but-
        # alive stragglers keep their strikes cleared), while an OLDER
        # message — e.g. an MQTT at-least-once redelivery of a dead device's
        # round-0 upload — is not evidence of life and must not reset the
        # strike counter.
        with self._agg_lock:
            try:  # a malformed/hostile ROUND_INDEX must not kill the handler
                # coerce ONCE and use the coerced value for both checks: a
                # transport delivering the index as a string would otherwise
                # keep liveness working while silently denying attendance
                # credit every round (strikes against healthy devices)
                up_round = int(msg.get(md.MSG_ARG_KEY_ROUND_INDEX))
            except (TypeError, ValueError):
                up_round = None
            if up_round == self.round_idx:
                self._uploaded_this_round.add(msg.get_sender_id())
            recent = up_round is not None and up_round >= self.round_idx - 1
        if recent:
            self.registry.note_participation(msg.get_sender_id())
        super().handle_message_receive_model(msg)

    def _probe_async(self, device_ids: list[int]) -> None:
        """Fire-and-forget status probes on a daemon thread: a probe to a
        black-holed device can block for the full connect timeout, and the
        candidate computation runs in the round-critical path (under
        _agg_lock) — dead devices must not stall live ones.  Best-effort by
        definition, so EVERY transport error is swallowed (gRPC raises
        RpcError, not OSError)."""
        if not device_ids:
            return
        from ..comm.message import Message

        def probe():
            for cid in device_ids:
                try:
                    self.send_message(Message(md.MSG_TYPE_S2C_CHECK_CLIENT_STATUS, 0, cid))
                except Exception:
                    pass  # genuinely offline: stays excluded until it answers

        import threading

        threading.Thread(target=probe, daemon=True).start()

    def _candidate_ids(self) -> list[int]:
        """Close out the PREVIOUS round's attendance (selected devices that
        never uploaded get a missed-selection strike — devices the sampler
        didn't pick are untouched), then schedule over live devices; probe
        every excluded device (even when all are excluded) so a recovered
        device's reply rejoins it.

        Behind ``extra.health_aware_selection`` the liveness-filtered pool is
        further narrowed by the :class:`~fedml_tpu.obs.health.ClientHealthLedger`
        scores the manager already maintains: degraded devices (slow EWMA
        round trips, deadline breaches, send failures) are admitted only
        when the healthy pool cannot fill the round — liveness says a phone
        ANSWERS, health says it answers IN TIME.  Without the flag the
        candidate set is reference-exact (liveness only)."""
        for cid in self.selected:
            if cid not in self._uploaded_this_round:
                self.registry.note_missed_selection(cid)
        self._uploaded_this_round = set()
        live = [c for c in self.client_ids if self.registry.is_live(c)]
        excluded = [c for c in self.client_ids if c not in live]
        self._probe_async(excluded)
        pool = live or self.client_ids
        if self.health_aware and len(pool) > self.per_round:
            healthy, degraded = self.health.partition(pool)
            if len(healthy) >= self.per_round:
                pool = healthy
            else:
                # fill the round from the least-degraded devices
                # (partition() returns degraded best-score-first)
                pool = healthy + degraded[: self.per_round - len(healthy)]
        return pool

    def _broadcast_model(self, msg_type: int) -> None:
        self._write_model_artifact()
        super()._broadcast_model(msg_type)

    def _write_model_artifact(self) -> None:
        if not self.global_model_file_path:
            return
        import jax

        from ..comm import wire

        path = Path(self.global_model_file_path)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_bytes(wire.encode_pytree(jax.device_get(self.aggregator.global_vars)))


def build_cross_device_server(cfg, dataset, model, backend: Optional[str] = None) -> ServerMNN:
    """TCP is the default device transport (phones connect as wire-speaking
    native clients)."""
    aggregator = build_aggregator(cfg, dataset, model)
    return ServerMNN(cfg, aggregator, backend=backend or "TCP")


class _CrossDeviceRunner:
    def __init__(self, cfg, dataset, model):
        self.cfg = cfg
        self.dataset = dataset
        self.model = model

    def run(self):
        # simulation-default backends ('', MESH, INPROC) have no meaning for
        # a device fleet — fall through to the TCP device transport
        backend = self.cfg.backend if self.cfg.backend not in ("", "MESH", "INPROC") else None
        server = build_cross_device_server(self.cfg, self.dataset, self.model,
                                           backend=backend)
        timeout = float(cfg_extra(self.cfg, "cross_device_timeout_s"))
        return server.run_until_done(timeout=timeout)


def create_cross_device_runner(cfg, dataset, model):
    return _CrossDeviceRunner(cfg, dataset, model)
