"""Cross-device platform ("BeeHive" in the reference) — the server side of
phone-fleet FL.

Parity with ``cross_device/server_mnn/fedml_server_manager.py:14``: a Python
server drives NON-Python device clients.  The reference serializes the
global model to MNN files (``write_tensor_dict_to_mnn``) and talks MQTT to
Android's C++ MobileNN trainer; the TPU build's devices speak the pytree
wire format over the TCP transport, and the reference's C++ trainer role is
filled by ``native/fedml_client.cpp`` (proven in CI by
tests/test_native_client.py + tests/test_cross_device.py).

The round protocol is the shared cross-silo one (message_define.py) — the
reference's cross-device server duplicates the cross-silo flow with MNN
serialization bolted on; here one server implementation serves both
platforms and only the transport/client language differ.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional

from ..cross_silo import build_aggregator
from ..cross_silo.server import FedMLServerManager


class ServerMNN(FedMLServerManager):
    """Cross-device server: cross-silo protocol + per-round global-model
    artifact dump (the reference's ``global_model_file_path`` MNN file,
    here the wire format every client language reads)."""

    def __init__(self, cfg, aggregator, backend: Optional[str] = None, logger=None):
        super().__init__(cfg, aggregator, backend=backend, logger=logger)
        extra = getattr(cfg, "extra", {}) or {}
        self.global_model_file_path = extra.get("global_model_file_path", "")

    def _write_model_artifact(self) -> None:
        if not self.global_model_file_path:
            return
        import jax

        from ..comm import wire

        path = Path(self.global_model_file_path)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_bytes(wire.encode_pytree(jax.device_get(self.aggregator.global_vars)))

    def _broadcast_model(self, msg_type: int) -> None:
        self._write_model_artifact()
        super()._broadcast_model(msg_type)


def build_cross_device_server(cfg, dataset, model, backend: Optional[str] = None) -> ServerMNN:
    """TCP is the default device transport (phones connect as wire-speaking
    native clients)."""
    aggregator = build_aggregator(cfg, dataset, model)
    return ServerMNN(cfg, aggregator, backend=backend or "TCP")


class _CrossDeviceRunner:
    def __init__(self, cfg, dataset, model):
        self.cfg = cfg
        self.dataset = dataset
        self.model = model

    def run(self):
        # simulation-default backends ('', MESH, INPROC) have no meaning for
        # a device fleet — fall through to the TCP device transport
        backend = self.cfg.backend if self.cfg.backend not in ("", "MESH", "INPROC") else None
        server = build_cross_device_server(self.cfg, self.dataset, self.model,
                                           backend=backend)
        timeout = float((getattr(self.cfg, "extra", {}) or {}).get("cross_device_timeout_s", 600.0))
        return server.run_until_done(timeout=timeout)


def create_cross_device_runner(cfg, dataset, model):
    return _CrossDeviceRunner(cfg, dataset, model)
