"""Streamed cohort execution — double-buffered gather around the vmapped round.

The population fit loop per round r:

    ids      = sampler.sample(r)                      (host, deterministic)
    batch    = store.gather_cohort(ids)               (host, disk/LRU)
    state    = store.gather_state(ids)                (host; mutable rows)
    outputs  = jit(cohort_round)(global, state, batch) (device, vmapped)
    store.scatter_state(ids, outputs.state)           (host)

The data gather is the host-side cost that would otherwise serialize with
device compute, so a ONE-DEEP prefetch pipeline overlaps it: while round r
runs on device, a worker thread gathers round r+1's cohort DATA.  Only the
immutable data rows are prefetched — per-client STATE is gathered on the
critical path, after round r's scatter, so a client sampled in consecutive
cohorts always trains from its freshest state (prefetching state would race
the scatter and silently fork a client's optimizer history).

``fedml_pop_prefetch_overlap_fraction`` records, per round, how much of the
gather wall time was hidden behind compute (1 = fully hidden, 0 = the round
blocked for the entire gather — e.g. round 0, which has nothing to overlap
with).  Gather/scatter timings land in the store's histograms; everything is
scrapable from the global registry next to the simulator's round timings.
"""

from __future__ import annotations

import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Optional

import numpy as np

from ..obs import registry as obsreg
from .sampler import HierarchicalCohortSampler
from .store import ShardedClientStore

__all__ = ["CohortPipeline"]

PREFETCH_OVERLAP = obsreg.REGISTRY.gauge(
    "fedml_pop_prefetch_overlap_fraction",
    "Fraction of the last cohort gather hidden behind device compute "
    "(1 = fully prefetched, 0 = the round blocked for the whole gather).",
)
COHORT_ROUNDS = obsreg.REGISTRY.counter(
    "fedml_pop_cohort_rounds_total",
    "Rounds executed through the population cohort pipeline.",
)


class CohortPipeline:
    """Owns the sampler+store pair and the one-deep data prefetch.

    Thread model (GL008-audited): ``_pending``/``_overlap_*`` are touched
    only by the fit-loop thread (``prefetch_round``/``obtain``/``close``);
    the worker thread runs ``_gather_job``, which reaches shared state only
    through :class:`ShardedClientStore` (every access under its ``_lock``)
    and the deterministic sampler (no mutable state past construction)."""

    def __init__(self, store: ShardedClientStore,
                 sampler: HierarchicalCohortSampler, prefetch: bool = True):
        self.store = store
        self.sampler = sampler
        self.prefetch = bool(prefetch)
        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="fedml-pop-prefetch"
        ) if self.prefetch else None
        self._pending: dict[int, Future] = {}
        self._overlap_sum = 0.0
        self._overlap_n = 0

    # -- gather side ----------------------------------------------------------
    def _gather_job(self, round_idx: int):
        t0 = time.perf_counter()
        ids = self.sampler.sample(round_idx)
        batch = self.store.gather_cohort(ids)
        return ids, batch, time.perf_counter() - t0

    def prefetch_round(self, round_idx: int) -> None:
        """Queue the data gather for ``round_idx`` on the worker thread
        (no-op when already pending or prefetch is disabled)."""
        if self._pool is not None and round_idx not in self._pending:
            self._pending[round_idx] = self._pool.submit(self._gather_job, round_idx)

    def obtain(self, round_idx: int):
        """The round's (ids, CohortBatch); blocks only for whatever part of
        the gather the prefetch did not hide, and records that fraction."""
        fut = self._pending.pop(round_idx, None)
        t0 = time.perf_counter()
        if fut is None:
            ids, batch, gather_s = self._gather_job(round_idx)
        else:
            ids, batch, gather_s = fut.result()
        waited = time.perf_counter() - t0
        overlap = 1.0 - min(1.0, waited / gather_s) if gather_s > 0 else 1.0
        PREFETCH_OVERLAP.set(overlap)
        self._overlap_sum += overlap
        self._overlap_n += 1
        COHORT_ROUNDS.inc()
        return ids, batch

    # -- bookkeeping ----------------------------------------------------------
    def overlap_mean(self) -> Optional[float]:
        return self._overlap_sum / self._overlap_n if self._overlap_n else None

    def close(self) -> None:
        self.store.flush()
        if self._pool is not None:
            # drop gathers that will never be consumed, then join the worker
            for fut in self._pending.values():
                fut.cancel()
            self._pending.clear()
            self._pool.shutdown(wait=True)

    @staticmethod
    def pad_ids(ids: np.ndarray, m_pad: int) -> np.ndarray:
        """Extend the cohort id vector to the mesh lane multiple by repeating
        the first id — pad lanes are sliced away before aggregation and
        never scattered, so their values are irrelevant; repeating an id the
        cohort already holds avoids touching an extra shard."""
        m = len(ids)
        if m_pad == m:
            return ids
        return np.concatenate([ids, np.full(m_pad - m, ids[0], np.int32)])
