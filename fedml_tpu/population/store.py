"""Sharded client-state store — per-client data/state on disk, cohorts in RAM.

The in-memory simulator stacks every client's padded shard into one
``(n_clients, capacity, ...)`` device array, so host (and HBM) footprint
scales with the POPULATION.  A million-client cross-device population does
not fit that way and never needs to: per round only the active cohort's rows
are touched.  FedJAX (PAPERS.md, 2108.02117) streams client data from host
storage for exactly this reason; this module is that layer for fedml_tpu.

Layout: the population of ``n_clients`` ids is cut into shards of
``shard_size`` CONTIGUOUS ids (shard ``s`` holds ``[s*shard_size,
min((s+1)*shard_size, n))``).  Each shard is one ``.npz`` file holding the
stacked padded data rows (``x``, ``y``), true sample counts, and — when the
algorithm carries per-client state (SCAFFOLD controls, personalization
vectors) — one stacked array per state leaf.  A bounded LRU keeps at most
``max_resident`` shards in host memory, so RSS scales with the number of
shards a cohort touches (the hierarchical sampler bounds that), never with
the population.

Shards materialize LAZILY: a shard file is written the first time the shard
is touched, from the ``builder`` callback (deterministic in the id range).
A 1M-client population therefore costs disk/CPU proportional to the ids
actually sampled — the property the bench's RSS floor asserts.

Client state is mutable: ``gather_state`` pulls cohort rows, the executor
runs the vmapped round, ``scatter_state`` writes the refreshed rows back
into the resident shard (dirty shards are rewritten on eviction and
``flush``).  Data rows are immutable, which is what lets the prefetch
thread gather cohort k+1's DATA while round k is still mutating state.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Optional

import numpy as np

from ..obs import registry as obsreg

__all__ = ["StoreSpec", "CohortBatch", "ShardedClientStore", "cyclic_builder"]

SHARD_LOADS = obsreg.REGISTRY.counter(
    "fedml_pop_shard_loads_total",
    "Shard lookups by the population store; result=hit served from the "
    "resident LRU, miss loaded from disk (or materialized by the builder).",
    labels=("result",),
)
RESIDENT_SHARDS = obsreg.REGISTRY.gauge(
    "fedml_pop_resident_shards",
    "Shards currently resident in the population store's LRU.",
)
GATHER_TIME = obsreg.REGISTRY.histogram(
    "fedml_pop_gather_seconds",
    "Wall time of one cohort gather (data or state) from the sharded store.",
)
SCATTER_TIME = obsreg.REGISTRY.histogram(
    "fedml_pop_scatter_seconds",
    "Wall time of one cohort state scatter back into the sharded store.",
)


@dataclass(frozen=True)
class StoreSpec:
    """Static shape of the population: how many clients, how their padded
    data rows look, and how the id space is cut into shards."""

    n_clients: int
    capacity: int           # padded samples per client (stack_clients semantics)
    x_shape: tuple          # per-SAMPLE feature shape
    x_dtype: str
    y_shape: tuple          # per-sample label shape (() for class ids)
    y_dtype: str
    shard_size: int

    @property
    def n_shards(self) -> int:
        return -(-self.n_clients // self.shard_size)

    def shard_range(self, sidx: int) -> tuple[int, int]:
        lo = sidx * self.shard_size
        return lo, min(lo + self.shard_size, self.n_clients)


@dataclass
class CohortBatch:
    """Stacked, vmap-ready cohort arrays in sampled-id order."""

    ids: np.ndarray      # (m,) int32
    x: np.ndarray        # (m, capacity, *x_shape)
    y: np.ndarray        # (m, capacity, *y_shape)
    counts: np.ndarray   # (m,) int32 true sample counts


def cyclic_builder(base_x: np.ndarray, base_y: np.ndarray, base_counts: np.ndarray
                   ) -> Callable[[int, int], tuple]:
    """Population builder that replicates a small base client stack
    cyclically: population client ``i`` carries base client ``i % n_base``'s
    rows.  The standard way to scale a real (small) federated dataset to a
    simulated 1M-id population without materializing 1M distinct shards of
    data up front."""
    n_base = base_x.shape[0]

    def build(lo: int, hi: int):
        rows = np.arange(lo, hi) % n_base
        return base_x[rows], base_y[rows], base_counts[rows]

    return build


class _Shard:
    """One resident shard: stacked arrays + a dirty bit for state writes."""

    __slots__ = ("arrays", "dirty")

    def __init__(self, arrays: dict):
        self.arrays = arrays
        self.dirty = False


class ShardedClientStore:
    """Disk-backed, LRU-cached per-client data + state.

    ``builder(lo, hi) -> (x, y, counts)`` materializes the data rows of a
    shard the first time it is touched; ``state_template`` (a per-client
    pytree of numpy arrays, or None) seeds every client's mutable state.
    All shard-map mutation happens under one lock — the prefetch thread
    gathers while the executor scatters.
    """

    _STATE_PREFIX = "state_"

    def __init__(self, root: str | Path, spec: StoreSpec,
                 builder: Optional[Callable[[int, int], tuple]] = None,
                 state_template=None, max_resident: int = 8):
        import jax

        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.spec = spec
        self.builder = builder
        self.max_resident = max(1, int(max_resident))
        self._lock = threading.Lock()
        self._resident: OrderedDict[int, _Shard] = OrderedDict()
        # state skeleton: leaf list + treedef from the template, so shard
        # files only need positionally-keyed stacked leaf arrays
        if state_template is not None:
            leaves, treedef = jax.tree_util.tree_flatten(
                jax.tree_util.tree_map(np.asarray, state_template))
            self._state_leaves = [np.asarray(l) for l in leaves]
            self._state_treedef = treedef
        else:
            self._state_leaves = None
            self._state_treedef = None

    # -- shard residency ------------------------------------------------------
    def _shard_path(self, sidx: int) -> Path:
        return self.root / f"shard_{sidx:06d}.npz"

    def _materialize(self, sidx: int) -> dict:
        lo, hi = self.spec.shard_range(sidx)
        if self.builder is None:
            raise FileNotFoundError(
                f"shard {sidx} ({self._shard_path(sidx)}) missing and the "
                "store has no builder to materialize it")
        x, y, counts = self.builder(lo, hi)
        arrays = {
            "x": np.ascontiguousarray(x),
            "y": np.ascontiguousarray(y),
            "counts": np.asarray(counts, np.int32),
        }
        if self._state_leaves is not None:
            n = hi - lo
            for i, leaf in enumerate(self._state_leaves):
                arrays[f"{self._STATE_PREFIX}{i}"] = np.broadcast_to(
                    leaf[None], (n,) + leaf.shape).copy()
        return arrays

    def _get_shard_locked(self, sidx: int) -> _Shard:  # graftlint: disable=GL004(caller holds _lock)
        shard = self._resident.get(sidx)
        if shard is not None:
            self._resident.move_to_end(sidx)
            SHARD_LOADS.inc(result="hit")
            return shard
        SHARD_LOADS.inc(result="miss")
        path = self._shard_path(sidx)
        if path.exists():
            with np.load(path) as z:
                arrays = {k: z[k] for k in z.files}
        else:
            arrays = self._materialize(sidx)
            self._write_shard(sidx, arrays)
        shard = _Shard(arrays)
        self._resident[sidx] = shard
        while len(self._resident) > self.max_resident:
            old_idx, old = self._resident.popitem(last=False)
            if old.dirty:
                self._write_shard(old_idx, old.arrays)
        RESIDENT_SHARDS.set(float(len(self._resident)))
        return shard

    def _write_shard(self, sidx: int, arrays: dict) -> None:
        # atomic replace: a crash mid-save must not leave a truncated npz
        # behind that poisons every later run
        path = self._shard_path(sidx)
        tmp = path.with_suffix(".npz.tmp")
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        tmp.replace(path)

    @staticmethod
    def _group_by_shard(ids: np.ndarray, shard_size: int):
        """[(shard_idx, positions-into-ids, rows-within-shard)] — one disk /
        LRU touch per distinct shard, whatever the cohort order."""
        ids = np.asarray(ids, np.int64)
        if ids.size == 0:
            return []
        sidx = ids // shard_size
        order = np.argsort(sidx, kind="stable")
        cuts = np.flatnonzero(np.diff(sidx[order])) + 1
        out = []
        for pos in np.split(order, cuts):
            s = int(sidx[pos[0]])
            out.append((s, pos, ids[pos] - s * shard_size))
        return out

    # -- cohort API -----------------------------------------------------------
    @property
    def has_state(self) -> bool:
        return self._state_leaves is not None

    def gather_cohort(self, ids) -> CohortBatch:
        """Stacked (m, capacity, ...) data arrays for ``ids``, in id order."""
        t0 = time.perf_counter()
        ids = np.asarray(ids, np.int32)
        m = len(ids)
        spec = self.spec
        x = np.empty((m, spec.capacity) + tuple(spec.x_shape), spec.x_dtype)
        y = np.empty((m, spec.capacity) + tuple(spec.y_shape), spec.y_dtype)
        counts = np.empty((m,), np.int32)
        with self._lock:
            for sidx, pos, rows in self._group_by_shard(ids, spec.shard_size):
                arrays = self._get_shard_locked(sidx).arrays
                x[pos] = arrays["x"][rows]
                y[pos] = arrays["y"][rows]
                counts[pos] = arrays["counts"][rows]
        GATHER_TIME.observe(time.perf_counter() - t0)
        return CohortBatch(ids=ids, x=x, y=y, counts=counts)

    def gather_state(self, ids):
        """Stacked per-client state pytree for ``ids`` (None when the
        algorithm is stateless).  Kept separate from :meth:`gather_cohort` so
        the prefetch thread can overlap the IMMUTABLE data gather while the
        current round is still scattering state."""
        if self._state_leaves is None:
            return None
        import jax

        t0 = time.perf_counter()
        ids = np.asarray(ids, np.int32)
        m = len(ids)
        stacked = [np.empty((m,) + leaf.shape, leaf.dtype)
                   for leaf in self._state_leaves]
        with self._lock:
            for sidx, pos, rows in self._group_by_shard(ids, self.spec.shard_size):
                arrays = self._get_shard_locked(sidx).arrays
                for i in range(len(stacked)):
                    stacked[i][pos] = arrays[f"{self._STATE_PREFIX}{i}"][rows]
        GATHER_TIME.observe(time.perf_counter() - t0)
        return jax.tree_util.tree_unflatten(self._state_treedef, stacked)

    def scatter_state(self, ids, state) -> None:
        """Write refreshed per-client state rows back into their shards
        (resident arrays are updated in place; shards are marked dirty and
        rewritten on eviction / flush)."""
        if self._state_leaves is None:
            return
        import jax

        t0 = time.perf_counter()
        ids = np.asarray(ids, np.int32)
        leaves = [np.asarray(l) for l in jax.tree_util.tree_leaves(
            jax.device_get(state))]
        with self._lock:
            for sidx, pos, rows in self._group_by_shard(ids, self.spec.shard_size):
                shard = self._get_shard_locked(sidx)
                for i, leaf in enumerate(leaves):
                    arr = shard.arrays[f"{self._STATE_PREFIX}{i}"]
                    if not arr.flags.writeable:  # fresh np.load gives RO views
                        arr = arr.copy()
                        shard.arrays[f"{self._STATE_PREFIX}{i}"] = arr
                    arr[rows] = leaf[pos]
                shard.dirty = True
        SCATTER_TIME.observe(time.perf_counter() - t0)

    def flush(self) -> None:
        """Persist every dirty resident shard (checkpoint boundary / close)."""
        with self._lock:
            for sidx, shard in self._resident.items():
                if shard.dirty:
                    self._write_shard(sidx, shard.arrays)
                    shard.dirty = False

    def drop_resident(self) -> None:
        """Flush then empty the LRU — used by tests to prove the on-disk
        shards are the source of truth."""
        self.flush()
        with self._lock:
            self._resident.clear()
        RESIDENT_SHARDS.set(0.0)
