"""Population-scale client subsystem (million-client cross-device simulation).

Three parts, composed by the ``MeshSimulator`` behind the registered
``extra.population_store`` flag (and usable standalone — the async/FedBuff
server item on the ROADMAP streams from the same store):

- :mod:`.store` — sharded on-disk client data + mutable per-client state
  with a bounded resident LRU (host RSS scales with the COHORT, not the
  population);
- :mod:`.sampler` — deterministic two-level (shard, then within-shard)
  cohort sampling honoring DeviceRegistry liveness and, behind
  ``extra.health_aware_selection``, ClientHealthLedger scores;
- :mod:`.cohorts` — the double-buffered prefetch pipeline that gathers
  cohort k+1 while cohort k runs through the vmapped round step.

``build_population_components`` is the config-driven assembly used by the
simulator: the (small) base dataset's stacked client rows seed a
``population_size``-client store via cyclic replication, so a 64-client
synthetic recipe can stand in for a 1M-id population without materializing
a million distinct shards up front.
"""

from __future__ import annotations

from typing import Optional

from ..core.flags import cfg_extra
from .cohorts import CohortPipeline
from .sampler import HierarchicalCohortSampler
from .store import CohortBatch, ShardedClientStore, StoreSpec, cyclic_builder

__all__ = [
    "CohortBatch", "CohortPipeline", "HierarchicalCohortSampler",
    "ShardedClientStore", "StoreSpec", "cyclic_builder",
    "build_population_components",
]


def build_population_components(
    cfg, root: str, base_x, base_y, base_counts, capacity: int,
    state_template=None, registry=None, health=None,
):
    """(store, sampler, pipeline) for a config + base client stack.

    ``base_*`` are the REAL clients' padded rows from ``stack_clients``
    (no mesh pad rows); population ids beyond the base replicate them
    cyclically.  ``registry``/``health`` flow into the sampler's masks —
    the simulator passes None (no live fleet), fleet-facing callers pass
    their DeviceRegistry / ClientHealthLedger.
    """
    n_base = int(base_x.shape[0])
    n_pop = int(cfg_extra(cfg, "population_size", n_base) or n_base)
    if n_pop < n_base:
        raise ValueError(
            f"population_size ({n_pop}) smaller than the base dataset's "
            f"client count ({n_base}) — shrink the dataset instead")
    shard_size = int(cfg_extra(cfg, "population_shard_size"))
    spec = StoreSpec(
        n_clients=n_pop,
        capacity=int(capacity),
        x_shape=tuple(base_x.shape[2:]),
        x_dtype=str(base_x.dtype),
        y_shape=tuple(base_y.shape[2:]),
        y_dtype=str(base_y.dtype),
        shard_size=shard_size,
    )
    store = ShardedClientStore(
        root, spec,
        builder=cyclic_builder(base_x, base_y, base_counts),
        state_template=state_template,
        max_resident=int(cfg_extra(cfg, "population_max_resident_shards")),
    )
    m = min(int(cfg.client_num_per_round), n_pop)
    spc = cfg_extra(cfg, "population_shards_per_cohort")
    sampler = HierarchicalCohortSampler(
        n_pop, m, shard_size, seed=int(cfg.random_seed),
        shards_per_cohort=int(spc) if spc else None,
        registry=registry, health=health,
        health_aware=bool(cfg_extra(cfg, "health_aware_selection")),
    )
    pipeline = CohortPipeline(
        store, sampler, prefetch=bool(cfg_extra(cfg, "population_prefetch")))
    return store, sampler, pipeline
