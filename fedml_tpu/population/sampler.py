"""Hierarchical cohort sampling over a sharded population.

Flat sampling over 1M ids would touch O(population) shards per cohort and
defeat the store's bounded residency.  The sampler is therefore TWO-LEVEL,
mirroring how production cross-device systems pick check-in cohorts:

1. **shard level** — a deterministic per-round permutation orders the
   shards; the cohort is drawn from the first ``shards_per_cohort`` of them
   (falling through to later shards only when the preferred ones cannot
   fill their quota), so a cohort touches a BOUNDED number of contiguous-id
   shards and the store's LRU stays small;
2. **client level** — within each visited shard, ids are drawn uniformly
   without replacement from the shard's eligible candidates.

Eligibility composes the same signals the live cross-device server uses:

- the :class:`~fedml_tpu.cross_device.DeviceRegistry` liveness mask — ids
  the registry has STRUCK OUT (missed too many consecutive selections) are
  excluded; ids the registry has never seen are assumed live, because a
  1M-simulated population never fully registers;
- behind ``extra.health_aware_selection``, the
  :class:`~fedml_tpu.obs.health.ClientHealthLedger` — degraded ids are
  deprioritized (sampled only when a shard's healthy pool cannot fill its
  quota), never permanently evicted — the same semantics as the cross-silo
  ``client_selection``.

Everything is driven by ``np.random.default_rng([seed, round_idx])``, so a
round's cohort is a pure function of (seed, round, masks): reproducible
across processes and immune to sampling-order drift.  When the cohort
covers the whole eligible population the sampler degenerates to "everyone,
in id order" — exactly the in-memory engine's behavior, which is what the
population-vs-in-memory parity test pins.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["HierarchicalCohortSampler"]


class HierarchicalCohortSampler:
    def __init__(self, n_clients: int, cohort_size: int, shard_size: int,
                 seed: int = 0, shards_per_cohort: Optional[int] = None,
                 registry=None, health=None, health_aware: bool = False):
        self.n_clients = int(n_clients)
        self.cohort_size = min(int(cohort_size), self.n_clients)
        self.shard_size = int(shard_size)
        self.seed = int(seed)
        self.n_shards = -(-self.n_clients // self.shard_size)
        if shards_per_cohort is None:
            # enough preferred shards that per-shard draws stay under half a
            # shard — keeps within-shard sampling meaningfully random while
            # bounding the store's working set
            shards_per_cohort = max(1, -(-2 * self.cohort_size // self.shard_size))
        self.shards_per_cohort = min(self.n_shards, max(1, int(shards_per_cohort)))
        self.registry = registry
        self.health = health
        self.health_aware = bool(health_aware)

    # -- masks ---------------------------------------------------------------
    def _live_mask(self, ids: np.ndarray) -> np.ndarray:
        """Registry liveness over a shard's id range; unknown ids are live
        (a simulated population never fully registers — only ids the
        registry explicitly struck out are excluded)."""
        if self.registry is None:
            return np.ones(len(ids), bool)
        devices = self.registry.devices
        mask = np.ones(len(ids), bool)
        for i, cid in enumerate(ids):
            if int(cid) in devices and not self.registry.is_live(int(cid)):
                mask[i] = False
        return mask

    def _split_by_health(self, ids: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(healthy, degraded-best-first) — ledger semantics, id-stable."""
        if not (self.health_aware and self.health is not None):
            return ids, np.empty(0, ids.dtype)
        healthy, degraded = self.health.partition(int(i) for i in ids)
        return (np.asarray(healthy, ids.dtype),
                np.asarray(degraded, ids.dtype) if degraded else np.empty(0, ids.dtype))

    # -- sampling ------------------------------------------------------------
    def sample(self, round_idx: int) -> np.ndarray:
        """The round's cohort: ``(cohort_size,)`` int32 ids, ascending.

        Deterministic in (seed, round_idx) and the current liveness/health
        masks.  If the eligible population cannot fill the cohort, excluded
        ids backfill (same "live or everyone" fallback as the cross-device
        candidate pass) so the jitted round always sees a full static lane
        count.
        """
        rng = np.random.default_rng([self.seed, int(round_idx)])
        shard_order = rng.permutation(self.n_shards)
        need = self.cohort_size
        quota = -(-self.cohort_size // self.shards_per_cohort)
        chosen: list[np.ndarray] = []
        leftover: list[np.ndarray] = []  # eligible but over-quota this pass
        deferred: list[np.ndarray] = []  # degraded/dead, kept as backfill
        for sidx in shard_order:
            if need <= 0:
                break
            lo = int(sidx) * self.shard_size
            hi = min(lo + self.shard_size, self.n_clients)
            ids = np.arange(lo, hi, dtype=np.int32)
            live = self._live_mask(ids)
            deferred.append(ids[~live])
            healthy, degraded = self._split_by_health(ids[live])
            deferred.append(degraded)
            take = min(quota, need, len(healthy))
            if take > 0:
                picked = rng.choice(healthy, size=take, replace=False)
                chosen.append(picked)
                need -= take
                leftover.append(np.setdiff1d(healthy, picked))
            else:
                leftover.append(healthy)
        if need > 0 and leftover:
            # every visited shard hit its quota and the cohort is still
            # short (uneven shard sizes): draw the remainder uniformly from
            # the eligible ids the quota pass left behind
            pool = np.concatenate(leftover)
            take = min(need, len(pool))
            if take > 0:
                chosen.append(rng.choice(pool, size=take, replace=False))
                need -= take
        if need > 0:
            # eligible pool exhausted at quota — backfill from the deferred
            # ids in deferral order (degraded best-first per shard, then
            # struck-out ids), deduped against the chosen set
            pool = np.concatenate(deferred) if deferred else np.empty(0, np.int32)
            taken = set(np.concatenate(chosen).tolist()) if chosen else set()
            fill = [i for i in pool.tolist() if i not in taken][:need]
            if fill:
                chosen.append(np.asarray(fill, np.int32))
                need -= len(fill)
        cohort = np.concatenate(chosen) if chosen else np.empty(0, np.int32)
        cohort.sort()
        return cohort.astype(np.int32)
