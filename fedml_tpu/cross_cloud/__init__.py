"""Cross-cloud FL ("Cheetah" in the reference) — silos in different clouds.

Parity with ``cross_cloud/fedml_server.py`` / ``fedml_client.py``: in the
reference these are the cross-silo initializers re-exported under the
cross-cloud entry (its server_manager duplicates the cross-silo one with
WAN-oriented transport config).  Here the same truth is explicit: a
cross-cloud deployment IS the cross-silo protocol over a WAN transport, so
the builders delegate to ``cross_silo`` with WAN-suited defaults applied —
routable transport (TCP/GRPC with an ip_config instead of loopback) and
bounded-wait straggler handling on (WAN silos fail more often than LAN
ones).
"""

from __future__ import annotations

from typing import Optional

from .. import constants as C
from ..core.flags import cfg_extra
from ..cross_silo import build_client, build_server


def _straggler_defaults(cfg):
    """WAN silos fail more than LAN ones: bounded-wait straggler handling is
    on by default (no silent override of explicit user choices)."""
    extra = dict(getattr(cfg, "extra", {}) or {})
    extra.setdefault("straggler_timeout_s", 60.0)   # graftlint: disable=GL001(writing WAN defaults into cfg.extra, not reading a flag)
    extra.setdefault("straggler_quorum_frac", 0.5)  # graftlint: disable=GL001(writing WAN defaults into cfg.extra, not reading a flag)
    cfg.extra = extra
    return cfg


def _wan_defaults(cfg):
    """Straggler defaults + a routable transport for distributed roles."""
    cfg = _straggler_defaults(cfg)
    if not cfg.backend or cfg.backend in ("INPROC", "MESH"):
        cfg.backend = C.COMM_BACKEND_TCP
    return cfg


class FedMLCrossCloudServer:
    def __init__(self, cfg, dataset, model, backend: Optional[str] = None):
        cfg = _wan_defaults(cfg)
        self.server = build_server(cfg, dataset, model, backend=backend or cfg.backend)

    def run(self, timeout: float = 3600.0):
        return self.server.run_until_done(timeout=timeout)


class FedMLCrossCloudClient:
    def __init__(self, cfg, dataset, model, rank: int, backend: Optional[str] = None):
        cfg = _wan_defaults(cfg)
        self.client = build_client(cfg, dataset, model, rank=rank, backend=backend or cfg.backend)

    def run(self):
        thread = self.client.run_in_thread()
        self.client.done.wait()
        thread.join(timeout=5.0)


class _CrossCloudRunner:
    """Platform runner for ``training_type='cross_cloud'`` (reference
    ``runner.py:19`` dispatches Cheetah the same way it does Octopus).

    The distinguishing cross-cloud capability is the workload Cheetah exists
    to host (``spotlight_prj/unitedllm/run_unitedllm.py``): federated LLM
    training where silos exchange ONLY LoRA adapters — enabled with
    ``extra.unitedllm: true``.  Non-LLM runs are the cross-silo protocol
    with WAN transport defaults."""

    def __init__(self, cfg, dataset, model):
        self.cfg = cfg
        self.dataset = dataset
        self.model = model

    def run(self, timeout: float = 3600.0):
        cfg = self.cfg
        llm_mode = bool(cfg_extra(cfg, "unitedllm"))
        if llm_mode:
            active = [
                f for f in ("enable_secagg", "enable_fhe", "enable_attack",
                            "enable_defense", "enable_dp")
                if getattr(cfg, f, False)
            ]
            if active:
                raise NotImplementedError(
                    f"trust features {active} are not wired into the "
                    "UnitedLLM adapter-exchange path; disable them or run "
                    "without extra.unitedllm"
                )
            from ..llm.unitedllm import (
                build_unitedllm_client,
                build_unitedllm_server,
                run_unitedllm_process_group,
            )

            if cfg.role == "server" and cfg.backend in ("INPROC", "MESH", ""):
                return run_unitedllm_process_group(cfg, self.dataset, timeout=timeout)[0]
            _wan_defaults(cfg)
            if cfg.role == "server":
                return build_unitedllm_server(cfg, self.dataset, backend=cfg.backend).run_until_done(timeout=timeout)
            client = build_unitedllm_client(cfg, self.dataset, rank=int(cfg.rank), backend=cfg.backend)
            thread = client.run_in_thread()
            client.done.wait()
            thread.join(timeout=5.0)
            return None
        # non-LLM cross-cloud IS the cross-silo platform (same builders, so
        # enable_secagg/enable_fhe dispatch to the secure managers — building
        # plain server/client here would silently downgrade WAN privacy) with
        # WAN transport defaults applied for distributed roles
        from ..cross_silo import create_cross_silo_runner

        if cfg.role == "server" and cfg.backend in ("INPROC", "MESH", ""):
            _straggler_defaults(cfg)  # keep the in-process transport
        else:
            _wan_defaults(cfg)
        return create_cross_silo_runner(cfg, self.dataset, self.model).run()


def create_cross_cloud_runner(cfg, dataset, model):
    return _CrossCloudRunner(cfg, dataset, model)
