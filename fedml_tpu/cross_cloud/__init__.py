"""Cross-cloud FL ("Cheetah" in the reference) — silos in different clouds.

Parity with ``cross_cloud/fedml_server.py`` / ``fedml_client.py``: in the
reference these are the cross-silo initializers re-exported under the
cross-cloud entry (its server_manager duplicates the cross-silo one with
WAN-oriented transport config).  Here the same truth is explicit: a
cross-cloud deployment IS the cross-silo protocol over a WAN transport, so
the builders delegate to ``cross_silo`` with WAN-suited defaults applied —
routable transport (TCP/GRPC with an ip_config instead of loopback) and
bounded-wait straggler handling on (WAN silos fail more often than LAN
ones).
"""

from __future__ import annotations

from typing import Optional

from .. import constants as C
from ..cross_silo import build_client, build_server


def _wan_defaults(cfg):
    """Apply cross-cloud transport defaults in place (no silent override of
    explicit user choices)."""
    extra = dict(getattr(cfg, "extra", {}) or {})
    extra.setdefault("straggler_timeout_s", 60.0)
    extra.setdefault("straggler_quorum_frac", 0.5)
    cfg.extra = extra
    if not cfg.backend or cfg.backend in ("INPROC", "MESH"):
        cfg.backend = C.COMM_BACKEND_TCP
    return cfg


class FedMLCrossCloudServer:
    def __init__(self, cfg, dataset, model, backend: Optional[str] = None):
        cfg = _wan_defaults(cfg)
        self.server = build_server(cfg, dataset, model, backend=backend or cfg.backend)

    def run(self, timeout: float = 3600.0):
        return self.server.run_until_done(timeout=timeout)


class FedMLCrossCloudClient:
    def __init__(self, cfg, dataset, model, rank: int, backend: Optional[str] = None):
        cfg = _wan_defaults(cfg)
        self.client = build_client(cfg, dataset, model, rank=rank, backend=backend or cfg.backend)

    def run(self):
        thread = self.client.run_in_thread()
        self.client.done.wait()
        thread.join(timeout=5.0)
