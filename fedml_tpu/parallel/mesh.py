"""Mesh construction for FL-on-TPU.

The reference scales by spawning processes (MPI ranks, torchrun DDP groups,
NCCL LocalAggregators — SURVEY.md §2.14 P1-P5).  Here the same strategies are
expressed as axes of one ``jax.sharding.Mesh``:

- simulation (P1-P3):  1-D ``("clients",)`` axis — each shard simulates a
  subset of clients; aggregation is a mean over the stacked-client dim that
  GSPMD lowers to an ICI all-reduce.
- intra-silo DP (P4):  ``("data",)`` axis — batch-sharded local SGD.
- hierarchical (P5):   2-D ``("silo", "data")`` — outer FL axis over DCN
  (multi-slice), inner DP axis over ICI.
- ZeRO-3 (P6):         parameter shardings over the ``data`` axis (GSPMD
  handles gather/scatter natively).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_CLIENTS = "clients"
AXIS_DATA = "data"
AXIS_SILO = "silo"
AXIS_MODEL = "model"  # tensor-parallel axis (beyond reference parity)
AXIS_SEQ = "seq"  # context/sequence-parallel axis (ring attention)

# shard_map moved to the jax top level (with check_vma) in newer jax; 0.4.x
# has it under experimental (with check_rep).  One shim so every shard_map
# call site works on both — pass **SHARD_MAP_UNCHECKED to skip the
# replication check.
try:
    from jax import shard_map  # noqa: F401  (jax >= 0.6)

    SHARD_MAP_UNCHECKED = {"check_vma": False}
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map  # noqa: F401

    SHARD_MAP_UNCHECKED = {"check_rep": False}


def make_mesh(
    axis_names: Sequence[str] = (AXIS_CLIENTS,),
    axis_sizes: Optional[Sequence[int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh over the available devices.

    If ``axis_sizes`` is None the first axis absorbs all devices.  Sizes may
    use -1 for "remaining devices" (like a reshape).
    """
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if axis_sizes is None:
        axis_sizes = [n] + [1] * (len(axis_names) - 1)
    sizes = list(axis_sizes)
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total > n:
        raise ValueError(f"mesh {dict(zip(axis_names, sizes))} needs {total} devices, have {n}")
    dev_array = np.array(devs[:total]).reshape(sizes)
    return Mesh(dev_array, tuple(axis_names))


def parse_mesh_shape(spec: str) -> tuple[list[str], list[int]]:
    """Parse ``"clients:8"`` / ``"silo:2,data:4"`` from Config.mesh_shape."""
    names, sizes = [], []
    for part in spec.split(","):
        name, _, size = part.strip().partition(":")
        names.append(name)
        sizes.append(int(size) if size else -1)
    return names, sizes


def mesh_from_config(cfg, devices=None) -> Mesh:
    if getattr(cfg, "mesh_shape", ""):
        names, sizes = parse_mesh_shape(cfg.mesh_shape)
        return make_mesh(names, sizes, devices)
    return make_mesh((AXIS_CLIENTS,), None, devices)


class SubmeshPlan:
    """A partition of the fleet's device array into disjoint per-job Meshes.

    Each lease is a contiguous slice of the device list reshaped to the SAME
    axis names/sizes, so a job's NamedShardings, pjit server fold, and AOT
    fingerprints (mesh shape is a fingerprint component) all resolve against
    its lease exactly as they would against a dedicated fleet of that shape —
    which is what makes submesh-vs-dedicated bitwise parity possible.
    """

    def __init__(self, submeshes: Sequence[Mesh], axis_names: Sequence[str],
                 axis_sizes: Sequence[int]):
        if not submeshes:
            raise ValueError("SubmeshPlan needs at least one submesh")
        self.submeshes = list(submeshes)
        self.axis_names = tuple(axis_names)
        self.axis_sizes = tuple(int(s) for s in axis_sizes)

    def __len__(self) -> int:
        return len(self.submeshes)

    def lease(self, index: int) -> Mesh:
        """The submesh of lease slot ``index`` (jobs hold a slot index, not
        a Mesh — the scheduler maps grant -> lease through this)."""
        return self.submeshes[index % len(self.submeshes)]

    def describe(self) -> dict:
        return {
            "jobs": len(self.submeshes),
            "shape": dict(zip(self.axis_names, self.axis_sizes)),
            "devices_per_job": int(np.prod(self.axis_sizes)),
        }


def carve_submeshes(
    axis_names: Sequence[str],
    axis_sizes: Sequence[int],
    n_jobs: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> SubmeshPlan:
    """Carve ``n_jobs`` disjoint contiguous submeshes of shape
    ``axis_names x axis_sizes`` out of the device list.

    Raises ``ValueError`` when the shapes do not tile the fleet (per-job
    size not concrete, or n_jobs x per-job devices exceeds the fleet) —
    callers fall back to the time-sliced gate on that error.
    """
    devs = list(devices if devices is not None else jax.devices())
    sizes = [int(s) for s in axis_sizes]
    if any(s <= 0 for s in sizes):
        raise ValueError(
            f"submesh shape {dict(zip(axis_names, sizes))} must be concrete "
            "(no -1 / zero axes) to tile the fleet")
    per = int(np.prod(sizes))
    n_jobs = int(n_jobs)
    if n_jobs < 1:
        raise ValueError(f"n_jobs must be >= 1, got {n_jobs}")
    if per * n_jobs > len(devs):
        raise ValueError(
            f"{n_jobs} submeshes of {per} devices need {per * n_jobs}, "
            f"fleet has {len(devs)}")
    subs = []
    for i in range(n_jobs):
        chunk = devs[i * per:(i + 1) * per]
        subs.append(Mesh(np.array(chunk).reshape(sizes), tuple(axis_names)))
    return SubmeshPlan(subs, axis_names, sizes)


def submesh_plan_from_config(cfg, devices=None) -> Optional[SubmeshPlan]:
    """Build the fleet partition from ``extra.mt_submesh_shape`` /
    ``mt_submesh_jobs``, or None (LOUDLY) when unset or the shapes do not
    tile the fleet — None means the control plane keeps the PR-14
    time-sliced gate, bit-identical."""
    import logging

    from ..core.flags import cfg_extra

    spec = cfg_extra(cfg, "mt_submesh_shape")
    if not spec:
        return None
    names, sizes = parse_mesh_shape(spec)
    devs = list(devices if devices is not None else jax.devices())
    n_jobs = cfg_extra(cfg, "mt_submesh_jobs")
    try:
        if n_jobs is None:
            per = int(np.prod([s for s in sizes if s > 0]))
            if any(s <= 0 for s in sizes) or per <= 0:
                raise ValueError(
                    f"submesh shape {spec!r} must be concrete to derive "
                    "mt_submesh_jobs")
            n_jobs = len(devs) // per
        return carve_submeshes(names, sizes, n_jobs, devs)
    except ValueError as e:
        logging.getLogger("fedml_tpu.parallel.mesh").warning(
            "mt_submesh_shape=%r rejected (%s); falling back to the "
            "time-sliced round gate", spec, e)
        return None


def round_up(n: int, multiple: int) -> int:
    """Smallest multiple of ``multiple`` >= ``n`` (client-axis padding math)."""
    return -(-n // multiple) * multiple


def pad_leading_axis_np(tree, n_target: int):
    """Zero-pad every leaf's leading axis to ``n_target`` rows (host-side).

    The one place client-axis pad-row semantics live: pad rows are ZEROS
    (zero-count dummies are never sampled, gathered for real lanes, or
    scattered to — engine invariants), used both at stack build and at
    checkpoint restore."""
    import numpy as np

    def pad(a):
        a = np.asarray(a)
        if n_target <= a.shape[0]:
            return a
        extra = np.zeros((n_target - a.shape[0],) + a.shape[1:], a.dtype)
        return np.concatenate([a, extra])

    return jax.tree_util.tree_map(pad, tree)


def client_sharding(mesh: Mesh, axis: str = AXIS_CLIENTS) -> NamedSharding:
    """Sharding for arrays with a leading stacked-clients dimension."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_leading_axis(tree, mesh: Mesh, axis: str = AXIS_CLIENTS, warn: bool = True):
    """Place a stacked pytree with its leading dim sharded over ``axis``.

    Leading dims not divisible by the axis size are replicated instead —
    correctness over parallelism for small client counts — but LOUDLY: a
    127-client stack on an 8-device axis silently losing all client
    parallelism is a perf cliff, so each distinct undivisible leading dim
    warns once per process.

    Multi-process aware: when the mesh spans hosts, arrays are assembled via
    make_array_from_callback (each host contributes its addressable shards).
    """
    import warnings

    from .multihost import make_global_array

    if axis not in mesh.shape:
        if axis != AXIS_CLIENTS:
            # an explicit axis that doesn't exist is a caller bug, not a
            # convention to paper over
            raise KeyError(
                f"mesh has no axis {axis!r} (axes: {mesh.axis_names}); "
                "pass one of the mesh's axes"
            )
        # the default stacked-clients axis on a mesh without one (e.g.
        # hierarchical's 2-D ("silo", "data")) shards over the FIRST axis —
        # the outer FL axis by this module's convention (P5 row above) —
        # and says so
        import warnings

        warnings.warn(
            f"shard_leading_axis: mesh has no {AXIS_CLIENTS!r} axis; "
            f"sharding the stacked-client dim over {mesh.axis_names[0]!r} "
            f"(the outer axis of {dict(mesh.shape)})",
            stacklevel=3,
        )
        axis = mesh.axis_names[0]
    size = mesh.shape[axis]

    def put(x):
        if x.ndim >= 1 and x.shape[0] % size == 0:
            spec = P(axis, *([None] * (x.ndim - 1)))
        else:
            if warn and x.ndim >= 1 and x.shape[0] > 1 and size > 1:
                key = (int(x.shape[0]), int(size))
                if key not in _undivisible_warned:
                    _undivisible_warned.add(key)
                    warnings.warn(
                        f"shard_leading_axis: leading dim {x.shape[0]} is not "
                        f"divisible by mesh axis {axis!r} size {size}; "
                        "REPLICATING instead — all parallelism over this axis "
                        "is lost for these arrays. Pad the client stack to a "
                        f"multiple of {size} (e.g. round client_num_per_round "
                        "up) to regain it.",
                        stacklevel=3,
                    )
            spec = P()
        return make_global_array(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, tree)


_undivisible_warned: set = set()


def replicate(tree, mesh: Mesh):
    from .multihost import make_global_array

    rep = replicated(mesh)
    return jax.tree_util.tree_map(lambda x: make_global_array(x, rep), tree)
