"""Decentralized-FL topologies (mixing matrices).

TPU-native replacement for ``core/distributed/topology/`` in the reference:
``SymmetricTopologyManager.generate_topology``
(``symmetric_topology_manager.py:21``) builds a ring plus random
Watts-Strogatz-style extra links and row-normalises; the asymmetric variant
drops symmetry.  Here the topology is a dense ``(n, n)`` mixing matrix used by
the decentralized algorithms (DSGD/PushSum) as a single matmul over stacked
client models — a gossip step becomes ``W @ params_matrix`` on the MXU rather
than per-neighbor message passing.
"""

from __future__ import annotations

import numpy as np


def ring_topology(n: int, symmetric: bool = True) -> np.ndarray:
    """Ring with self-loops, row-normalized (uniform over {self, prev, next})."""
    W = np.zeros((n, n), dtype=np.float32)
    for i in range(n):
        W[i, i] = 1.0
        W[i, (i - 1) % n] = 1.0
        W[i, (i + 1) % n] = 1.0
    if not symmetric:
        for i in range(n):
            W[i, (i - 1) % n] = 0.0
    return W / W.sum(axis=1, keepdims=True)


def symmetric_topology(n: int, neighbor_num: int, seed: int = 0) -> np.ndarray:
    """Ring + random symmetric extra links, row-normalized.

    Semantics of the reference's ``SymmetricTopologyManager`` (undirected ring
    with ``neighbor_num`` target degree via random rewiring), deterministic in
    ``seed`` instead of global numpy state.
    """
    rng = np.random.RandomState(seed)
    A = np.zeros((n, n), dtype=np.float32)
    for i in range(n):
        A[i, i] = 1.0
        A[i, (i - 1) % n] = 1.0
        A[i, (i + 1) % n] = 1.0
    extra = max(0, neighbor_num - 2)
    for i in range(n):
        candidates = [j for j in range(n) if j != i and A[i, j] == 0]
        if not candidates:
            continue
        picks = rng.choice(candidates, size=min(extra, len(candidates)), replace=False)
        for j in picks:
            A[i, j] = 1.0
            A[j, i] = 1.0  # keep symmetric
    return A / A.sum(axis=1, keepdims=True)


def asymmetric_topology(n: int, neighbor_num: int, seed: int = 0) -> np.ndarray:
    """Directed ring + random out-links, row-normalized (PushSum-style)."""
    rng = np.random.RandomState(seed)
    A = np.zeros((n, n), dtype=np.float32)
    for i in range(n):
        A[i, i] = 1.0
        A[i, (i + 1) % n] = 1.0
        candidates = [j for j in range(n) if j != i and A[i, j] == 0]
        extra = max(0, neighbor_num - 1)
        if candidates and extra:
            picks = rng.choice(candidates, size=min(extra, len(candidates)), replace=False)
            for j in picks:
                A[i, j] = 1.0
    return A / A.sum(axis=1, keepdims=True)


def column_stochastic(W: np.ndarray) -> np.ndarray:
    """Renormalize a nonnegative mixing matrix so each column sums to 1.

    PushSum requires column stochasticity: each source node's pushed mass
    totals 1, so the weight column ``w' = W @ w`` evolves away from all-ones
    and the de-biased ratio ``x / w`` converges to the *uniform* average on a
    directed graph (row-stochastic W instead converges to the stationary-
    distribution-weighted consensus).  Self-loops guarantee every column has a
    nonzero entry.
    """
    col = W.sum(axis=0, keepdims=True)
    return (W / np.where(col == 0, 1.0, col)).astype(np.float32)


def fully_connected(n: int) -> np.ndarray:
    return np.full((n, n), 1.0 / n, dtype=np.float32)
