"""Parameter/activation sharding rules (GSPMD).

The reference scales LLM training with DeepSpeed ZeRO-3 param sharding
(``train/llm/distributed.py:52-68``, ``ds_z3_bf16_config.json`` — SURVEY.md
§2.14 P6).  On TPU the same thing is a set of ``PartitionSpec`` rules: fully
sharding parameters over the ``data`` axis IS ZeRO-3 (GSPMD inserts the
gather/scatter), and a ``model`` axis adds Megatron-style tensor parallelism
the reference never had.

Rules are (path-regex -> PartitionSpec) pairs matched against flattened
parameter paths, the idiom used by t5x/maxtext-style trainers.
"""

from __future__ import annotations

import re
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import AXIS_DATA, AXIS_MODEL, AXIS_SEQ

# (regex over 'layer_0/attn/wq/kernel'-style paths, spec builder)
# Specs assume kernels are (in, out) or (in, heads, head_dim).
TRANSFORMER_RULES = [
    # attention projections: shard heads/out over model axis, in over data (zero3)
    (r".*attn/w[qkv]/kernel", lambda dp, tp: P(dp, tp, None)),
    (r".*attn/wo/kernel", lambda dp, tp: P(tp, None, dp)),
    # mlp: gate/up shard out over model; down shards in over model
    (r".*mlp/w_(gate|up)/kernel", lambda dp, tp: P(dp, tp)),
    (r".*mlp/w_down/kernel", lambda dp, tp: P(tp, dp)),
    # embeddings / head: vocab over model axis
    (r".*embed/embedding", lambda dp, tp: P(tp, dp)),
    (r".*lm_head/kernel", lambda dp, tp: P(dp, tp)),
    # norms replicated
    (r".*norm.*/scale", lambda dp, tp: P()),
]


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def partition_specs(params, rules=TRANSFORMER_RULES, dp_axis: Optional[str] = AXIS_DATA,
                    tp_axis: Optional[str] = AXIS_MODEL, mesh: Optional[Mesh] = None):
    """Pytree of PartitionSpecs for ``params`` by first-matching rule.

    Axes absent from ``mesh`` (or of size 1) degrade to None in the spec, so
    the same rules serve pure-DP, pure-TP, and hybrid meshes.
    """
    def axis_or_none(name):
        if name is None or mesh is None:
            return name
        return name if (name in mesh.shape and mesh.shape[name] > 1) else None

    dp = axis_or_none(dp_axis)
    tp = axis_or_none(tp_axis)

    def spec_for(path, leaf):
        ps = _path_str(path)
        for pattern, builder in rules:
            if re.fullmatch(pattern, ps):
                spec = builder(dp, tp)
                # trim/extend to leaf rank
                entries = list(spec)[: leaf.ndim]
                entries += [None] * (leaf.ndim - len(entries))
                # drop shardings that don't divide the dim evenly
                entries = [
                    e if e is not None and leaf.shape[i] % (mesh.shape[e] if mesh else 1) == 0 else (e if e is None else None)
                    for i, e in enumerate(entries)
                ]
                return P(*entries)
        return P()  # replicate by default

    return jax.tree_util.tree_map_with_path(spec_for, params)


def named_shardings(params, mesh: Mesh, **kw):
    specs = partition_specs(params, mesh=mesh, **kw)
    return jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), specs,
                                  is_leaf=lambda x: isinstance(x, P))


def shard_params(params, mesh: Mesh, **kw):
    sh = named_shardings(params, mesh, **kw)
    return jax.tree_util.tree_map(jax.device_put, params, sh)


def batch_sharding(mesh: Mesh, dp_axis: str = AXIS_DATA, seq_axis: Optional[str] = None):
    """(batch, seq, ...) activation sharding: batch over dp, seq over sp."""
    dp = dp_axis if dp_axis in mesh.shape and mesh.shape[dp_axis] > 1 else None
    sp = seq_axis if seq_axis and seq_axis in mesh.shape and mesh.shape[seq_axis] > 1 else None
    return NamedSharding(mesh, P(dp, sp))
