"""Multi-host (multi-process) execution — the MULTIPROCESS backend.

Parity target: the reference's MPI simulation platform
(``simulation/mpi/fedavg/FedAvgAPI.py:13`` — 1 server + N worker ranks over
``mpi4py``) and its NCCL/gloo process groups.  TPU-native translation: the
SAME single-controller-looking program runs on every host
(multi-controller JAX); ``jax.distributed.initialize`` wires the
coordination service, the global ``Mesh`` spans all hosts' devices, and the
collectives that the MPI ranks did by hand (send/recv of model state) become
GSPMD all-reduces over ICI/DCN.  No actor hierarchy, no rank-0 parameter
server: every process executes the identical jitted round and holds the
identical replicated global state.

Run the same script on every host with either
- env: JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID
  (standard jax.distributed envs also work: COORDINATOR_ADDRESS, ...), or
- cfg.extra: coordinator_address / num_processes / process_id.

CPU-backed multi-process (gloo collectives) is first-class for CI: the
2-process test in ``tests/test_multihost.py`` asserts numerics equal the
single-process mesh run.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional

import jax
import numpy as np

log = logging.getLogger("fedml_tpu.parallel.multihost")

_initialized = False


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def _externally_initialized() -> bool:
    """True when jax.distributed was already initialized by someone else
    (standard multi-host launchers call it before user code)."""
    try:
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None) is not None
    except Exception:
        return False


def ensure_initialized(cfg=None) -> bool:
    """Initialize jax.distributed from config/env if requested and not yet up.

    Returns True when running multi-process after the call.  Safe to call
    multiple times and from single-process runs (no-ops).
    """
    global _initialized
    if _initialized:
        return jax.process_count() > 1
    from ..core.flags import cfg_extra

    coord = (
        cfg_extra(cfg, "coordinator_address")
        or os.environ.get("JAX_COORDINATOR_ADDRESS")
        or os.environ.get("COORDINATOR_ADDRESS")
    )
    if not coord:
        # single-process (or externally-initialized) run; jax.process_count
        # may initialize the backend, which is fine at this point
        return jax.process_count() > 1
    if _externally_initialized():
        # the launcher (or user script) already called
        # jax.distributed.initialize — adopt it rather than crash on a
        # second initialize
        _initialized = True
        return jax.process_count() > 1
    nproc = int(cfg_extra(cfg, "num_processes") or os.environ.get("JAX_NUM_PROCESSES") or 0)
    pid = cfg_extra(cfg, "process_id", os.environ.get("JAX_PROCESS_ID"))
    kwargs: dict[str, Any] = {"coordinator_address": coord}
    if nproc:
        kwargs["num_processes"] = nproc
    if pid is not None:
        kwargs["process_id"] = int(pid)
    jax.distributed.initialize(**kwargs)
    _initialized = True
    log.info(
        "jax.distributed up: process %d/%d, %d global devices (%d local)",
        jax.process_index(), jax.process_count(), len(jax.devices()), len(jax.local_devices()),
    )
    return True


def make_global_array(x, sharding) -> jax.Array:
    """Build a globally-sharded array from a host-replicated numpy array.

    Every process holds the identical FULL array (fedml_tpu's data loading is
    deterministic per seed, so all hosts materialize the same shards — no
    host-to-host scatter needed); each contributes only its addressable
    shards, sliced out by index.  Single-process this is just device_put.
    """
    if not is_multiprocess():
        return jax.device_put(x, sharding)
    x = np.asarray(x)
    return jax.make_array_from_callback(x.shape, sharding, lambda idx: x[idx])


def fetch_replicated(tree):
    """device_get for multi-controller: replicated outputs are addressable
    on every host, so plain device_get works; this wrapper documents the
    invariant and asserts it in debug runs."""
    return jax.device_get(tree)


def sync_global_devices(tag: str = "fedml_tpu") -> None:
    if is_multiprocess():
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)
