"""Streaming-fold accumulators — host numpy and pjit-sharded device forms.

The cross-silo streaming accumulator (``cross_silo/server.py``) folds each
arriving model-reply leaf into a running weighted sum.  The historical form
is a list of host f32 numpy arrays — fine while the exchanged tree fits one
host, wrong once it doesn't (the 1810.11112 observation: at scale the server
fold must shard, not gather).  This module gives the fold two interchangeable
backends behind one interface:

- :class:`HostStreamAccumulator` — the exact historical numpy math, kept
  bit-identical (the default; also the journal's restore form).
- :class:`ShardedStreamAccumulator` — every per-leaf sum lives as a jax
  array under a :class:`~jax.sharding.NamedSharding` on a 1-D device mesh
  (``parallel.mesh``); each arriving leaf is ``device_put`` to its shard
  owners and folded there under jit, so no device ever materializes a whole
  leaf it doesn't own, and the finalized global inherits the shardings.

Both compute ``sum_i w_i * x_i`` in f32 and finalize as
``((sum + w_delta * base) / total).astype(dtype)``.  Because every step is an
IEEE elementwise f32 op (the weights are cast to f32 before the multiply on
both paths), the sharded fold is **bitwise** the host fold — asserted by
test and by the ``federated_lora`` bench.

Engaged behind ``extra.server_shard_fold``; unset keeps the host path and
its bytes untouched.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import numpy as np

__all__ = [
    "FieldStreamAccumulator",
    "HostStreamAccumulator",
    "ShardedStreamAccumulator",
    "make_stream_accumulator",
]


# Bitwise discipline: XLA contracts `a + w * x` inside one fused executable
# into an FMA, which rounds ONCE where the host numpy fold rounds the
# multiply and the add separately — so the device fold would drift from the
# host fold by 1 ulp on ~half the elements.  Each step therefore runs as its
# own single-op executable (mul, add, div+cast): nothing to contract, and
# every op is the same IEEE f32 operation numpy performs.

@functools.lru_cache(maxsize=None)
def _mul_add_fns():
    import jax

    mul = jax.jit(lambda x, w: w * x)
    add = jax.jit(lambda a, b: a + b)
    return mul, add


@functools.lru_cache(maxsize=None)
def _div_cast_fn(dtype_str: str):
    import jax

    dt = np.dtype(dtype_str)
    return jax.jit(lambda a, tot: (a / tot).astype(dt))


class HostStreamAccumulator:
    """The historical host-side fold: one f32 numpy array per leaf."""

    kind = "host"

    def __init__(self, templates: Sequence[np.ndarray],
                 sums: Optional[Sequence[np.ndarray]] = None):
        if sums is not None:
            self._sums = [np.asarray(s, np.float32) for s in sums]
        else:
            self._sums = [np.zeros(np.shape(t), np.float32) for t in templates]

    def fold_leaf(self, i: int, w: float, arr) -> None:
        self._sums[i] += np.float32(w) * np.asarray(arr, dtype=np.float32)

    def fold_partial_leaf(self, i: int, arr) -> None:
        """Merge a PRE-FOLDED weighted partial (hierarchical aggregation,
        ``cross_silo/edge.py``): a direct add, no weight multiply — the
        partial already carries ``sum_c w_c * x_c``, and adding it verbatim
        is the bitwise continuation of the child node's fold (a ``* f32(1.0)``
        would be value-identical but is omitted on principle: the tree must
        introduce no op the flat fold didn't run)."""
        self._sums[i] += np.asarray(arr, dtype=np.float32)

    def host_sums(self) -> list:
        """The per-leaf f32 sums as host arrays (journal snapshot form)."""
        return [np.asarray(s) for s in self._sums]

    def finalize(self, templates: Sequence[np.ndarray], w_delta: float,
                 total: float) -> list:
        out = []
        for i, t in enumerate(templates):
            acc = self._sums[i]
            if w_delta:
                # delta senders contributed w*(model - global): add their
                # share of the base model back before normalizing
                acc = acc + np.float32(w_delta) * np.asarray(t, dtype=np.float32)
            out.append((acc / np.float32(total)).astype(np.asarray(t).dtype))
        return out


class ShardedStreamAccumulator:
    """Per-leaf f32 sums as NamedSharding'd jax arrays on a 1-D mesh.

    Each leaf is sharded along its first axis divisible by the mesh size
    (replicated otherwise — small norms/scales are noise at fold scale);
    ``fold_leaf`` places the arriving leaf with the accumulator's sharding
    and runs the add under jit, so the fold executes on the shard-owning
    devices.  No donation: XLA:CPU buffer donation is unsupported (and has
    corrupted the heap for scanned programs — see ROADMAP), and the fold
    arrays are small relative to the model programs.
    """

    kind = "sharded"

    def __init__(self, templates: Sequence[np.ndarray], mesh=None,
                 sums: Optional[Sequence[np.ndarray]] = None):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from . import mesh as meshlib

        if mesh is None:
            mesh = meshlib.make_mesh((meshlib.AXIS_DATA,))
        self.mesh = mesh
        size = int(np.prod(list(mesh.shape.values())))

        def leaf_sharding(t):
            shape = np.shape(t)
            for ax, dim in enumerate(shape):
                if dim >= size and dim % size == 0:
                    spec = [None] * len(shape)
                    spec[ax] = mesh.axis_names[0]
                    return NamedSharding(mesh, P(*spec))
            return NamedSharding(mesh, P())

        self._shardings = [leaf_sharding(t) for t in templates]
        init = (sums if sums is not None
                else [np.zeros(np.shape(t), np.float32) for t in templates])
        self._sums = [
            jax.device_put(jnp.asarray(np.asarray(s), jnp.float32), sh)
            for s, sh in zip(init, self._shardings)
        ]
        # process-wide single-op jits (see the bitwise-discipline note above)
        self._mul, self._add = _mul_add_fns()

    def fold_leaf(self, i: int, w: float, arr) -> None:
        import jax
        import jax.numpy as jnp

        x = jax.device_put(jnp.asarray(np.asarray(arr), jnp.float32),
                           self._shardings[i])
        self._sums[i] = self._add(self._sums[i], self._mul(x, jnp.float32(w)))

    def fold_partial_leaf(self, i: int, arr) -> None:
        """Direct add of a pre-folded weighted partial — see the host form;
        the single-op ``add`` jit keeps it bitwise the numpy add."""
        import jax
        import jax.numpy as jnp

        x = jax.device_put(jnp.asarray(np.asarray(arr), jnp.float32),
                           self._shardings[i])
        self._sums[i] = self._add(self._sums[i], x)

    def host_sums(self) -> list:
        import jax

        return [np.asarray(jax.device_get(s)) for s in self._sums]

    def finalize(self, templates: Sequence[np.ndarray], w_delta: float,
                 total: float) -> list:
        """Normalize ON DEVICE under jit: the output leaves keep their
        NamedShardings, so the updated global state stays sharded."""
        import jax
        import jax.numpy as jnp

        out = []
        for i, t in enumerate(templates):
            div_cast = _div_cast_fn(np.asarray(t).dtype.str)
            acc = self._sums[i]
            if w_delta:
                base = jax.device_put(
                    jnp.asarray(np.asarray(t), jnp.float32), self._shardings[i])
                acc = self._add(acc, self._mul(base, jnp.float32(w_delta)))
            out.append(div_cast(acc, jnp.float32(total)))
        return out


class FieldStreamAccumulator:
    """Modular-field sibling of the f32 fold: per-leaf int64 sums over a
    masking ring (streaming pairwise-mask SecAgg, ISSUE 15).

    Field sums are EXACT — the whole point of the mod-field protocol — so
    there is no weight multiply (secure aggregation cannot scale updates it
    cannot see) and no rounding question.  Reduction is LAZY: raw int64
    adds accumulate and the modulus comes out only when read, which is safe
    for ``~2^63 / modulus`` folds before overflow (2^32 folds at the M31
    prime — far past any cohort) and keeps the per-fold cost at one vector
    add, on par with the f32 fold.
    """

    kind = "field"

    def __init__(self, templates: Sequence[np.ndarray], modulus: int,
                 sums: Optional[Sequence[np.ndarray]] = None):
        self.modulus = int(modulus)
        init = sums if sums is not None else templates
        self._sums = [np.zeros(np.shape(t), np.int64) if sums is None
                      else np.asarray(t, np.int64) for t in init]
        self._pending = 0
        # lazy-reduction headroom: folds of values < modulus before a reduce
        self._reduce_every = max(1, (2**62) // self.modulus)

    def fold_leaf(self, i: int, arr) -> None:
        self._sums[i] += np.asarray(arr, dtype=np.int64)
        if i == 0:
            self._pending += 1
            if self._pending >= self._reduce_every:
                self._reduce()

    def _reduce(self) -> None:
        for i, s in enumerate(self._sums):
            np.mod(s, self.modulus, out=self._sums[i])
        self._pending = 0

    def host_sums(self) -> list:
        """Per-leaf field totals, reduced mod the ring."""
        self._reduce()
        return [np.asarray(s) for s in self._sums]


def make_stream_accumulator(templates: Sequence[np.ndarray], *,
                            sharded: bool = False, mesh=None,
                            sums: Optional[Sequence[np.ndarray]] = None):
    """Accumulator factory: ``sharded`` selects the NamedSharding fold
    (``extra.server_shard_fold``); default is the bit-identical host form."""
    if sharded:
        return ShardedStreamAccumulator(templates, mesh=mesh, sums=sums)
    return HostStreamAccumulator(templates, sums=sums)
