"""Customized workflow jobs — DAG nodes that drive the real verticals.

Parity with ``workflow/customized_jobs/`` in the reference
(``train_job.py:1`` — ``TrainJob`` wraps a ``fedml launch`` yaml, polls run
status, exposes outputs downstream; ``model_deploy_job.py`` — deploys a model
and exposes the endpoint), re-built on this repo's own verticals:

- :class:`LaunchJob` packages a job yaml into the agent spool
  (:class:`~fedml_tpu.sched.launch.FedMLLaunchManager`), waits on the shared
  ``JobDB`` until an agent has run it, and exposes the run's ``output.json``
  to downstream jobs.
- :class:`DeployJob` registers the upstream artifact as a
  :class:`~fedml_tpu.serving.deploy.ModelCard`, drives a
  :class:`~fedml_tpu.serving.deploy.ModelDeployScheduler` (or the
  master/worker :class:`~fedml_tpu.serving.deploy_protocol.DeployMasterManager`)
  to readiness, and exposes a live ``predict`` callable.

Dependency feeding: a job's ``run(**inputs)`` receives its dependencies'
outputs keyed by job name (``Workflow.run``).  LaunchJob serializes those
inputs to ``__workflow_inputs__.json`` inside the packaged workspace so the
launched process can read them (the reference threads outputs through
dynamically-built yamls; a file in the package is the spool-transport
equivalent).
"""

from __future__ import annotations

import json
import logging
import time
from pathlib import Path
from typing import Any, Optional

from .workflow import Job, JobStatus

log = logging.getLogger("fedml_tpu.workflow")


def _jsonable(tree: Any) -> Any:
    """Best-effort JSON projection of dependency outputs: non-serializable
    values (live scheduler handles, callables) are replaced by their repr —
    a launched subprocess can only consume data, not live objects."""
    try:
        json.dumps(tree)
        return tree
    except (TypeError, ValueError):
        pass
    if isinstance(tree, dict):
        return {str(k): _jsonable(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return [_jsonable(v) for v in tree]
    return repr(tree)


class LaunchJob(Job):
    """A workflow node wrapping ``fedml launch job.yaml``.

    Reference ``TrainJob`` behavior (``customized_jobs/train_job.py``): build
    the run package, submit, poll status until terminal, surface the run's
    output.  The agent consuming the spool may live in another thread or
    another process — status is read from the shared sqlite ``JobDB``, not
    from an in-memory agent handle.

    Output contract: the launched job may write an ``output.json`` in its run
    directory (its cwd); its parsed content is merged into this job's output
    dict alongside ``run_id`` / ``run_dir`` / ``returncode``.
    """

    def __init__(self, name: str, yaml_path: str, spool_dir: str,
                 timeout: float = 600.0, poll_s: float = 0.3):
        super().__init__(name)
        self.yaml_path = str(yaml_path)
        self.spool_dir = str(spool_dir)
        self.timeout = timeout
        self.poll_s = poll_s
        self.run_id: Optional[str] = None

    def run(self, **inputs) -> dict:
        from ..sched.agent import JobDB
        from ..sched.launch import FedMLLaunchManager, JobSpec

        self.status = JobStatus.RUNNING
        try:
            spec = JobSpec.from_yaml(self.yaml_path)
            ws = Path(self.yaml_path).parent / spec.workspace
            inputs_file = None
            if inputs:
                # feed dependency outputs INTO the package: the launched
                # process reads __workflow_inputs__.json from its cwd
                inputs_file = ws / "__workflow_inputs__.json"
                inputs_file.write_text(json.dumps(_jsonable(inputs)))
            mgr = FedMLLaunchManager(self.spool_dir)
            try:
                pkg = mgr.build_package(spec, base_dir=str(Path(self.yaml_path).parent))
            finally:
                # the inputs belong to ONE launch; leaking the file into the
                # source workspace would feed stale inputs to the next
                # package built from it (and dirty the user's tree)
                if inputs_file is not None:
                    try:
                        inputs_file.unlink()
                    except OSError:
                        pass
            self.run_id = pkg.stem
            log.info("workflow job %s: launched %s", self.name, self.run_id)

            db = JobDB(str(Path(self.spool_dir) / "jobs.sqlite"))
            deadline = time.time() + self.timeout
            row = None
            while time.time() < deadline:
                row = db.get(self.run_id)
                if row and row["status"] in ("FINISHED", "FAILED"):
                    break
                time.sleep(self.poll_s)
            else:
                raise TimeoutError(
                    f"run {self.run_id} not terminal after {self.timeout}s "
                    f"(last status: {(row or {}).get('status', 'never claimed')}"
                    " — is an agent sweeping this spool?)"
                )
            run_dir = Path(self.spool_dir) / "runs" / self.run_id
            if row["status"] == "FAILED":
                tail = ""
                lp = row.get("log_path")
                if lp and Path(lp).exists():
                    tail = Path(lp).read_text()[-2000:]
                raise RuntimeError(
                    f"run {self.run_id} FAILED (rc={row.get('returncode')}):\n{tail}"
                )
            out = {
                "run_id": self.run_id,
                "run_dir": str(run_dir),
                "returncode": row.get("returncode"),
            }
            out_file = run_dir / "output.json"
            if out_file.exists():
                out.update(json.loads(out_file.read_text()))
            self.output = out
            self.status = JobStatus.FINISHED
            return out
        except BaseException as e:
            self.status = JobStatus.FAILED
            self.error = e
            raise


class DeployJob(Job):
    """A workflow node that deploys an upstream model artifact and exposes a
    live endpoint (reference ``model_deploy_job.py``).

    The artifact is found in the dependencies' outputs: the first dep dict
    carrying ``params_path`` wins (``model`` / ``classes`` / ``model_name`` /
    ``model_version`` ride along when present); explicit constructor kwargs
    override.  Deploys via an injected
    :class:`~fedml_tpu.serving.deploy.ModelDeployScheduler` (in-proc,
    process replicas) or an injected
    :class:`~fedml_tpu.serving.deploy_protocol.DeployMasterManager`
    (master/worker placement over the FL transport).

    Output: ``{"endpoint", "ready_replicas", "predict"}`` where ``predict``
    is a callable routing through the live gateway — downstream jobs (or the
    caller) can serve requests immediately.
    """

    def __init__(self, name: str, endpoint: str, scheduler=None, master=None,
                 model_name: str = "", model_version: str = "v1",
                 model: str = "", classes: int = 0, params_path: str = "",
                 replicas: int = 1, ready_timeout: float = 120.0):
        super().__init__(name)
        if (scheduler is None) == (master is None):
            raise ValueError("pass exactly one of scheduler= or master=")
        self.endpoint = endpoint
        self.scheduler = scheduler
        self.master = master
        self.model_name = model_name
        self.model_version = model_version
        self.model = model
        self.classes = classes
        self.params_path = params_path
        self.replicas = replicas
        self.ready_timeout = ready_timeout

    def _resolve_card(self, inputs: dict):
        from ..serving.deploy import ModelCard

        src: dict = {}
        for dep_out in inputs.values():
            if isinstance(dep_out, dict) and dep_out.get("params_path"):
                src = dep_out
                break
        params_path = self.params_path or src.get("params_path", "")
        if not params_path:
            raise ValueError(
                f"deploy job {self.name!r}: no params_path — neither passed "
                "explicitly nor found in any dependency output"
            )
        return ModelCard(
            name=self.model_name or src.get("model_name", self.endpoint),
            version=self.model_version,
            model=self.model or src.get("model", "lr"),
            classes=int(self.classes or src.get("classes", 10)),
            params_path=params_path,
        )

    def run(self, **inputs) -> dict:
        self.status = JobStatus.RUNNING
        try:
            card = self._resolve_card(inputs)
            if self.scheduler is not None:
                out = self._run_scheduler(card)
            else:
                out = self._run_master(card)
            self.output = out
            self.status = JobStatus.FINISHED
            return out
        except BaseException as e:
            self.status = JobStatus.FAILED
            self.error = e
            raise

    def _run_scheduler(self, card) -> dict:
        sched = self.scheduler
        sched.cards.register(card)
        sched.deploy(self.endpoint, card.name, card.version, replicas=self.replicas)
        if not sched.wait_ready(self.endpoint, replicas=self.replicas,
                                timeout=self.ready_timeout):
            raise TimeoutError(
                f"endpoint {self.endpoint!r} not ready after {self.ready_timeout}s"
            )
        ep = sched.endpoints[self.endpoint]
        return {
            "endpoint": self.endpoint,
            "ready_replicas": len(ep.ready_ports()),
            "predict": lambda request, _s=sched: _s.predict(self.endpoint, request),
        }

    def _run_master(self, card) -> dict:
        master = self.master
        master.deploy(self.endpoint, card, replicas=self.replicas)
        if not master.wait_ready(self.endpoint, self.replicas,
                                 timeout=self.ready_timeout):
            raise TimeoutError(
                f"endpoint {self.endpoint!r}: "
                f"{len(master.ready_targets(self.endpoint))}/{self.replicas} "
                f"replicas ready after {self.ready_timeout}s"
            )
        return {
            "endpoint": self.endpoint,
            "ready_replicas": len(master.ready_targets(self.endpoint)),
            "predict": lambda request, _m=master: _m.predict(self.endpoint, request),
        }
