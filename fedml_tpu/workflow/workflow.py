"""Workflow engine — DAG of jobs.

Parity with ``workflow/workflow.py:42`` (``Workflow``: topological execution,
loop detection) and ``workflow/jobs.py:9,43`` (``Job``/``JobStatus``).  Jobs
are arbitrary callables (the reference wraps ``fedml launch`` yaml runs —
here a job may wrap a simulator run, a bench, a deploy, a shell step).
"""

from __future__ import annotations

import enum
import logging
import time
from typing import Any, Callable, Optional

log = logging.getLogger("fedml_tpu.workflow")


class JobStatus(str, enum.Enum):
    PROVISIONING = "PROVISIONING"
    RUNNING = "RUNNING"
    FINISHED = "FINISHED"
    FAILED = "FAILED"
    UNDETERMINED = "UNDETERMINED"


class Job:
    """Reference ``Job`` shape: named unit with run/status/kill."""

    def __init__(self, name: str, fn: Optional[Callable[..., Any]] = None):
        self.name = name
        self.fn = fn
        self.status = JobStatus.PROVISIONING
        self.output: Any = None
        self.error: Optional[BaseException] = None
        self.dependencies: list[str] = []

    def run(self, **inputs) -> Any:
        self.status = JobStatus.RUNNING
        try:
            self.output = self.fn(**inputs) if self.fn else None
            self.status = JobStatus.FINISHED
            return self.output
        except BaseException as e:
            self.status = JobStatus.FAILED
            self.error = e
            raise

    def kill(self) -> None:
        self.status = JobStatus.UNDETERMINED


class Workflow:
    """Reference ``Workflow``: add_job(job, dependencies=[...]), topological
    run, loops forbidden."""

    def __init__(self, name: str = "workflow"):
        self.name = name
        self.jobs: dict[str, Job] = {}
        self._run_order: list[str] = []

    def add_job(self, job: Job, dependencies: Optional[list] = None) -> None:
        deps = [d.name if isinstance(d, Job) else str(d) for d in (dependencies or [])]
        job.dependencies = deps
        if job.name in self.jobs:
            raise ValueError(f"duplicate job name {job.name!r}")
        self.jobs[job.name] = job

    def _toposort(self) -> list[str]:
        for j in self.jobs.values():
            for d in j.dependencies:
                if d not in self.jobs:
                    raise ValueError(f"job {j.name!r} depends on unknown job {d!r}")
        order, seen, visiting = [], set(), set()

        def visit(name: str):
            if name in seen:
                return
            if name in visiting:
                raise ValueError(f"workflow contains a cycle through {name!r}")
            visiting.add(name)
            for d in self.jobs[name].dependencies:
                visit(d)
            visiting.discard(name)
            seen.add(name)
            order.append(name)

        for name in self.jobs:
            visit(name)
        return order

    def run(self) -> dict[str, Any]:
        """Execute jobs in dependency order; each job receives its
        dependencies' outputs as kwargs keyed by job name."""
        self._run_order = self._toposort()
        outputs: dict[str, Any] = {}
        for name in self._run_order:
            job = self.jobs[name]
            inputs = {d: outputs[d] for d in job.dependencies}
            log.info("workflow %s: running job %s", self.name, name)
            t0 = time.perf_counter()
            outputs[name] = job.run(**inputs)
            log.info("workflow %s: job %s finished in %.2fs", self.name, name, time.perf_counter() - t0)
        return outputs

    def get_workflow_status(self) -> JobStatus:
        statuses = {j.status for j in self.jobs.values()}
        if JobStatus.FAILED in statuses:
            return JobStatus.FAILED
        if JobStatus.RUNNING in statuses:
            return JobStatus.RUNNING
        if statuses == {JobStatus.FINISHED}:
            return JobStatus.FINISHED
        return JobStatus.PROVISIONING
