from .flow import FedMLAlgorithmFlow, FedMLExecutor, Params

__all__ = ["FedMLAlgorithmFlow", "FedMLExecutor", "Params"]
