"""Algorithm Flow DSL — compose custom FL protocols as named steps.

Parity with ``core/distributed/flow/fedml_flow.py:20`` (FedMLAlgorithmFlow /
FedMLExecutor / Params): a user defines executor classes (e.g. Client,
Server) with task methods, registers an ordered sequence of named flows, and
every node runs the same flow program — each step executes on the nodes
whose executor class owns it, and its output Params travel to the next
step's nodes over the comm layer.

Differences by design (the reference's flow engine is ~500 LoC of reflective
message plumbing):
- Fan-in is explicit: a step whose class has multiple nodes upstream starts
  once messages from ALL upstream nodes arrive (the reference approximates
  this with per-flow handler bookkeeping); the collected Params list is
  passed to the task, which is exactly what aggregation steps need.
- Tags: ONCE (default) and FINISH (last step, auto-applied by build()), as
  in the reference; ``loop(times=k)`` replays the registered sequence k
  times, replacing the reference's manual re-registration idiom.
- Payloads ride the pytree wire format like every other transport user (no
  pickle).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

from ..comm.comm_manager import FedMLCommManager
from ..comm.message import Message

log = logging.getLogger("fedml_tpu.flow")

MSG_TYPE_FLOW_FINISH = 999  # broadcast when the FINISH step ran (reference MSG_TYPE_FLOW_FINISH)
MSG_TYPE_FLOW_BASE = 1000  # flow steps get msg types BASE + step_index
MSG_ARG_KEY_FLOW_STEP = "flow_step"
# payload entries ride as individual message params ("fp_<key>") so each key
# takes the control-JSON or tensor-wire path on its own merits (a mixed dict
# under one key would defeat the Message codec's split)
FLOW_PARAM_PREFIX = "fp_"


class Params(dict):
    """Reference ``alg_frame/params.py``: a dict with attribute access."""

    def add(self, key: str, value) -> None:
        self[key] = value

    def __getattr__(self, key):
        try:
            return self[key]
        except KeyError as e:
            raise AttributeError(key) from e


class FedMLExecutor:
    """Reference ``fedml_executor.py:4``: a node role with an id and the set
    of peer ids; subclasses define task methods used as flow steps."""

    def __init__(self, id: int, neighbor_id_list: list[int]):
        self.id = id
        self.neighbor_id_list = list(neighbor_id_list)
        self.params: Optional[Params] = None

    def get_params(self) -> Optional[Params]:
        return self.params

    def set_params(self, params: Optional[Params]) -> None:
        self.params = params


class FedMLAlgorithmFlow(FedMLCommManager):
    ONCE = "FLOW_TAG_ONCE"
    FINISH = "FLOW_TAG_FINISH"

    def __init__(self, cfg, executor: FedMLExecutor, executors_by_class: dict[str, list[int]],
                 backend: Optional[str] = None):
        """``executors_by_class``: {class_name: [node ids]} — the global cast
        list every node shares (the reference discovers it via neighbor
        status messages; here it is explicit and deterministic)."""
        super().__init__(cfg, rank=executor.id, size=sum(len(v) for v in executors_by_class.values()),
                         backend=backend)
        self.executor = executor
        self.executor_cls = type(executor).__name__
        self.executors_by_class = executors_by_class
        self._steps: list[tuple[str, Callable, str, str]] = []  # (name, task, cls, tag)
        self._built = False
        self._inbox: dict[int, dict[int, Params]] = {}  # step -> sender -> params
        self._fired: set[int] = set()  # step indices already executed locally
        self._executed: list[str] = []
        self.done = threading.Event()
        self._lock = threading.Lock()

    # -- DSL -----------------------------------------------------------------
    def add_flow(self, flow_name: str, executor_task: Callable, flow_tag: str = ONCE) -> None:  # graftlint: disable=GL008(the flow graph is built single-threaded before run() starts the comm loop; handlers only ever read _steps after build())
        # the owning class is the second-to-last qualname component
        # ("Outer.<locals>.ClientEx.local_training" -> "ClientEx")
        parts = executor_task.__qualname__.split(".")
        cls_name = parts[-2] if len(parts) >= 2 else parts[0]
        self._steps.append((f"{flow_name}#{len(self._steps)}", executor_task, cls_name, flow_tag))

    def loop(self, times: int) -> None:
        """Replay the currently registered sequence ``times-1`` more times."""
        base = list(self._steps)
        for _ in range(max(times, 1) - 1):
            for name, task, cls, tag in base:
                self.add_flow(name.split("#")[0], task, tag)

    def build(self) -> None:
        if not self._steps:
            raise ValueError("no flows registered")
        name, task, cls, _ = self._steps[-1]
        self._steps[-1] = (name, task, cls, self.FINISH)
        self._built = True

    # -- engine --------------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(MSG_TYPE_FLOW_FINISH, self._handle_finish)
        for idx in range(len(self._steps)):
            self.register_message_receive_handler(MSG_TYPE_FLOW_BASE + idx, self._handle_step_message)

    def _handle_finish(self, msg: Message) -> None:
        self.done.set()
        self.finish()

    def run_until_finish(self, timeout: float = 120.0) -> list[str]:
        """Start the flow program; returns the list of locally executed step
        names (order is the protocol trace for this node)."""
        assert self._built, "call build() first"
        thread = self.run_in_thread()
        # step 0 starts unconditionally on its owning class (reference
        # _on_ready_to_run_flow)
        if self._steps[0][2] == self.executor_cls:
            with self._lock:
                self._fired.add(0)
            self._execute_step(0, upstream=[])
        if not self.done.wait(timeout):
            self.finish()
            raise TimeoutError(f"flow did not finish in {timeout}s (node {self.executor.id})")
        thread.join(timeout=5.0)
        return self._executed

    def _upstream_nodes(self, step_idx: int) -> list[int]:
        if step_idx == 0:
            return []
        prev_cls = self._steps[step_idx - 1][2]
        return self.executors_by_class.get(prev_cls, [])

    def _handle_step_message(self, msg: Message) -> None:
        step_idx = int(msg.get(MSG_ARG_KEY_FLOW_STEP))
        params = Params({
            k[len(FLOW_PARAM_PREFIX):]: v
            for k, v in msg.all_params().items() if k.startswith(FLOW_PARAM_PREFIX)
        })
        with self._lock:
            box = self._inbox.setdefault(step_idx, {})
            box[msg.get_sender_id()] = params
            ready = set(box) >= set(self._upstream_nodes(step_idx))
            # at-least-once transports (MQTT redelivery, retries) can deliver a
            # duplicate or late upstream message after fan-in was satisfied —
            # the step must fire exactly once, and the upstream list must be
            # snapshotted while the lock is held
            if ready and step_idx not in self._fired:
                self._fired.add(step_idx)
                upstream = [box[i] for i in sorted(box)]
            else:
                return
        self._execute_step(step_idx, upstream=upstream)

    def _execute_step(self, step_idx: int, upstream: list[Params]) -> None:
        name, task, cls, tag = self._steps[step_idx]
        if cls != self.executor_cls:
            return
        # fan-in: a single upstream node passes its Params directly; multiple
        # upstream nodes pass the ordered list (aggregation semantics)
        if len(upstream) == 1:
            self.executor.set_params(upstream[0])
        elif upstream:
            self.executor.set_params(Params(upstream_list=upstream))
        out = task(self.executor)
        self._executed.append(name)  # graftlint: disable=GL008(appended only on the receive loop; callers read _executed after done.wait(), ordered by the Event)
        if tag == self.FINISH:
            # tell every other node the program is over (reference
            # _handle_flow_finish broadcast)
            for ids in self.executors_by_class.values():
                for dest in ids:
                    if dest != self.executor.id:
                        self.send_message(Message(MSG_TYPE_FLOW_FINISH, self.executor.id, dest))
            self.done.set()
            self.finish()
            return
        next_cls = self._steps[step_idx + 1][2]
        payload = dict(out) if out else {}
        for dest in self.executors_by_class.get(next_cls, []):
            msg = Message(MSG_TYPE_FLOW_BASE + step_idx + 1, self.executor.id, dest)
            msg.add_params(MSG_ARG_KEY_FLOW_STEP, step_idx + 1)
            for k, v in payload.items():
                msg.add_params(FLOW_PARAM_PREFIX + str(k), v)
            self.send_message(msg)


def run_flow_group(cfg, flows: list[FedMLAlgorithmFlow], timeout: float = 120.0) -> dict[int, list[str]]:
    """Run a cast of flow nodes on threads over the in-proc fabric (hermetic
    twin of the reference's test_fedml_flow.py MPI launch)."""
    results: dict[int, list[str]] = {}
    errors: list[Exception] = []

    def runner(f: FedMLAlgorithmFlow):
        try:
            results[f.executor.id] = f.run_until_finish(timeout=timeout)
        except Exception as e:  # surfaced by the caller
            errors.append(e)

    threads = [threading.Thread(target=runner, args=(f,), daemon=True) for f in flows]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout + 10)
    if errors:
        raise errors[0]
    return results
