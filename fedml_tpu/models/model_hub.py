"""Dataset-keyed model factory.

Parity with the reference's ``model/model_hub.py:19`` (``create(args, output_dim)``):
dispatch on ``(args.model, args.dataset)`` to a model instance.  Returns a
flax.linen Module; parameter init happens in the trainer frame so the factory
stays cheap and side-effect free.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from ..arguments import Config
from ..core.flags import cfg_extra
from . import cnn_zoo, resnet, rnn, simple


def create(cfg: Config, output_dim: int) -> Any:
    name = cfg.model.lower()
    norm = getattr(cfg, "norm", "batch")
    # compute dtype threads into the conv/matmul path (params stay f32);
    # without this the whole CNN zoo silently runs f32 on the MXU's slow path
    dtype = jnp.bfloat16 if cfg.compute_dtype == "bfloat16" else jnp.float32
    if name in ("lr", "logistic_regression"):
        return simple.LogisticRegression(num_classes=output_dim)
    if name in ("cnn", "cnn_dropout"):
        only_digits = cfg.dataset in ("mnist", "fashionmnist")
        return simple.FedAvgCNN(num_classes=output_dim, only_digits=only_digits)
    if name in ("simple-cnn", "cifar_cnn", "cnn_web"):
        return simple.CifarCNN(num_classes=output_dim)
    if name == "mlp":
        # extra.mlp_hidden widens the hidden layer (comm-compression benches
        # need leaves past the qsgd8 block size); default matches upstream
        return simple.MLP(num_classes=output_dim,
                          hidden=int(cfg_extra(cfg, "mlp_hidden")))
    # extra.fused_blocks routes the CIFAR-ResNet conv epilogues through the
    # fused Pallas kernel (ops/pallas/fused_block.py); cfg_extra also honors
    # a direct cfg attribute, so a recipe-level `fused_blocks: true` lands
    # here without a dedicated field
    fused = bool(cfg_extra(cfg, "fused_blocks"))
    if name == "resnet20":
        return resnet.resnet20(output_dim, norm, dtype, fused=fused)
    if name == "resnet32":
        return resnet.resnet32(output_dim, norm, dtype, fused=fused)
    if name == "resnet44":
        return resnet.resnet44(output_dim, norm, dtype, fused=fused)
    if name == "resnet56":
        return resnet.resnet56(output_dim, norm, dtype, fused=fused)
    if name in ("resnet18_gn", "resnet_gn"):
        # BN-free escape hatch (reference model/cv/resnet_gn.py)
        return resnet.resnet20(output_dim, "group", dtype)
    if name in ("rnn", "char_lstm", "rnn_originalfedavg"):
        return rnn.CharLSTM(vocab_size=output_dim)
    if name in ("rnn_stackoverflow", "word_lstm"):
        return rnn.WordLSTM(vocab_size=output_dim)
    # CNN zoo breadth (reference model_hub.py:66-73 + model/cv/vgg.py);
    # small_input picks the CIFAR stride-1 stem for small images — derived
    # from the dataset's spec shape (public accessor applies the loader's
    # name normalization) so the knowledge lives in ONE place
    from ..data.loader import dataset_spec

    spec = dataset_spec(cfg.dataset)
    small = spec is not None and len(spec[0]) == 3 and spec[0][0] <= 36
    if name == "mobilenet":
        return cnn_zoo.MobileNetV1(num_classes=output_dim, norm=norm, dtype=dtype, small_input=small)
    if name in ("mobilenet_v3", "mobilenetv3"):
        return cnn_zoo.MobileNetV3Small(num_classes=output_dim, norm=norm, dtype=dtype, small_input=small)
    if name in ("efficientnet", "efficientnet_b0"):
        return cnn_zoo.EfficientNetB0(num_classes=output_dim, norm=norm, dtype=dtype, small_input=small)
    if name in ("vgg11", "vgg"):
        return cnn_zoo.VGG(num_classes=output_dim, depth=11, norm=norm, dtype=dtype)
    if name == "vgg16":
        return cnn_zoo.VGG(num_classes=output_dim, depth=16, norm=norm, dtype=dtype)
    raise ValueError(f"unknown model {cfg.model!r} (dataset {cfg.dataset!r})")
