"""Character/word RNN models for the text FL benchmarks.

Parity with the reference's ``model/nlp/rnn.py``: ``RNN_OriginalFedAvg``
(shakespeare next-char: 8-dim embedding -> 2xLSTM(256) -> dense vocab) and
``RNN_StackOverFlow`` (next-word prediction: embed(96) -> LSTM(670) -> dense).

Implemented with ``nn.scan``-wrapped ``OptimizedLSTMCell`` so the sequence loop
is a single XLA while/scan (compiler-friendly control flow), not a python loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn


class StackedLSTM(nn.Module):
    hidden: int
    layers: int = 2

    @nn.compact
    def __call__(self, x):
        # x: (batch, seq, feat) -> (batch, seq, hidden)
        for _ in range(self.layers):
            cell = nn.OptimizedLSTMCell(self.hidden)
            scan = nn.RNN(cell)
            x = scan(x)
        return x


class CharLSTM(nn.Module):
    """Shakespeare next-char model (``RNN_OriginalFedAvg``)."""

    vocab_size: int = 90
    embed_dim: int = 8
    hidden: int = 256

    @nn.compact
    def __call__(self, tokens, train: bool = True):
        # tokens: (batch, seq) int32 -> logits (batch, seq, vocab)
        x = nn.Embed(self.vocab_size, self.embed_dim)(tokens)
        x = StackedLSTM(self.hidden, layers=2)(x)
        return nn.Dense(self.vocab_size)(x)


class WordLSTM(nn.Module):
    """StackOverflow next-word model (``RNN_StackOverFlow``)."""

    vocab_size: int = 10004
    embed_dim: int = 96
    hidden: int = 670

    @nn.compact
    def __call__(self, tokens, train: bool = True):
        x = nn.Embed(self.vocab_size, self.embed_dim)(tokens)
        x = StackedLSTM(self.hidden, layers=1)(x)
        x = nn.Dense(self.embed_dim)(x)
        return nn.Dense(self.vocab_size)(x)
