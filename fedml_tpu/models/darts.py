"""DARTS-style differentiable supernet for FedNAS.

Parity target: ``model/cv/darts/model_search.py`` (``Network``) driving the
FedNAS algorithm (``simulation/mpi/fednas/FedNASAggregator.py:9`` aggregates
model weights AND architecture alphas).  The reference search space is the
full 8-op DARTS cell; this supernet keeps the DARTS mechanics — MixedOp =
softmax(alpha)-weighted op sum, cells stacked, alphas as a separate
parameter collection — over a compact 4-op space sized for federated rounds
(the search dynamics, alternating w/alpha updates, and genotype derivation
are what FedNAS exercises; op-menu breadth is config).

TPU notes: every candidate op runs every step (dense weighted sum — no
data-dependent branching), which is exactly what the MXU wants; alphas live
in the ``arch`` collection so the optimizer/aggregator can treat them
separately from weights (flax mutable collections).
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

OPS = ("conv3", "conv5", "skip", "zero")


class MixedOp(nn.Module):
    features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, alpha):
        """alpha: (n_ops,) logits for THIS edge."""
        w = nn.softmax(alpha)
        c3 = nn.relu(nn.Conv(self.features, (3, 3), padding="SAME", dtype=self.dtype)(x))
        c5 = nn.relu(nn.Conv(self.features, (5, 5), padding="SAME", dtype=self.dtype)(x))
        skip = x if x.shape[-1] == self.features else nn.Conv(self.features, (1, 1), dtype=self.dtype)(x)
        zero = jnp.zeros_like(c3)
        return w[0] * c3 + w[1] * c5 + w[2] * skip + w[3] * zero


class DARTSSuperNet(nn.Module):
    """n_cells cells of two MixedOp edges each; alphas: (n_cells, 2, n_ops)
    stored in the 'arch' param collection."""

    num_classes: int
    n_cells: int = 2
    features: int = 16
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        alphas = self.param(
            "alphas", lambda k: jnp.zeros((self.n_cells, 2, len(OPS)), jnp.float32)
        )
        x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(self.features, (3, 3), padding="SAME", dtype=self.dtype)(x))
        for c in range(self.n_cells):
            h1 = MixedOp(self.features, self.dtype, name=f"cell{c}_op0")(x, alphas[c, 0])
            h2 = MixedOp(self.features, self.dtype, name=f"cell{c}_op1")(h1, alphas[c, 1])
            x = h2
            if c < self.n_cells - 1:
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def split_arch_params(params):
    """(weights, alphas) partition of the supernet param tree — FedNAS
    aggregates them separately (reference __aggregate_weight/__update_arch)."""
    weights = {k: v for k, v in params.items() if k != "alphas"}
    return weights, params["alphas"]


def derive_genotype(alphas) -> list[list[str]]:
    """argmax op per edge (reference genotype derivation, minus the zero op
    which encodes 'prune this edge')."""
    picks = jnp.argmax(alphas[..., : len(OPS) - 1], axis=-1)  # exclude zero
    return [[OPS[int(op)] for op in cell] for cell in picks]
