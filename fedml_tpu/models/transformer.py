"""Llama-style decoder-only transformer (flax.linen).

Capability target: the reference's LLM stack trains HF Llama-2/TinyLlama via
torch + DeepSpeed (``train/llm/``, SURVEY.md §2.15).  This is the TPU-native
model: RMSNorm, rotary embeddings, (grouped-query) attention, SwiGLU MLP —
built for GSPMD sharding (pure einsum/Dense, static shapes) with optional
ring attention when a ``seq`` mesh axis is present (long-context,
SURVEY.md §5) and ``jax.checkpoint``-friendly block structure.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 8
    d_ff: int = 1408  # ~8/3 * d_model rounded
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True  # jax.checkpoint each block (HBM <-> FLOPs trade)
    # remat policy: "full" recomputes everything in bwd; "dots" saves matmul
    # outputs and recomputes only cheap elementwise/norm ops (much less
    # recompute FLOPs for a modest HBM cost — the right default for MFU)
    remat_policy: str = "dots"
    # lm_head matmul dtype; bf16 keeps the (tokens, vocab) projection on the
    # MXU fast path (loss still upcasts logits to f32 for the softmax)
    logits_dtype: Any = jnp.bfloat16

    @classmethod
    def tiny(cls, vocab_size: int = 1024):
        return cls(vocab_size=vocab_size, d_model=128, n_layers=2, n_heads=4,
                   n_kv_heads=4, d_ff=352, max_seq_len=512)

    @classmethod
    def llama_7b(cls):
        """Llama-2-7B shape (the reference FedLLM target model)."""
        return cls(vocab_size=32000, d_model=4096, n_layers=32, n_heads=32,
                   n_kv_heads=32, d_ff=11008, max_seq_len=4096)


class RMSNorm(nn.Module):
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
        return (x * jax.lax.rsqrt(var + self.eps)).astype(x.dtype) * scale


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary position embedding, HF-Llama half-split (rotate_half) convention
    so pretrained Llama-2 checkpoints (the stated llama_7b target) load without
    permuting wq/wk.  x: (b, s, h, d)."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[:, :, None, None].astype(jnp.float32) * freqs  # (b, s, 1, d/2)
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., : d // 2], x[..., d // 2 :]
    y1 = x1 * cos - x2 * sin
    y2 = x2 * cos + x1 * sin
    return jnp.concatenate([y1, y2], axis=-1).astype(x.dtype)


class Attention(nn.Module):
    cfg: TransformerConfig
    mesh: Optional[Any] = None
    seq_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        hd = cfg.d_model // cfg.n_heads
        dense = partial(nn.DenseGeneral, use_bias=False, dtype=cfg.dtype)
        q = dense(features=(cfg.n_heads, hd), name="wq")(x)
        k = dense(features=(cfg.n_kv_heads, hd), name="wk")(x)
        v = dense(features=(cfg.n_kv_heads, hd), name="wv")(x)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        if cfg.n_kv_heads != cfg.n_heads:  # GQA: repeat kv heads
            rep = cfg.n_heads // cfg.n_kv_heads
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        if self.mesh is not None and self.seq_axis and self.mesh.shape[self.seq_axis] > 1:
            from ..ops.ring_attention import ring_attention
            from ..parallel.mesh import AXIS_DATA, AXIS_MODEL

            out = ring_attention(
                q, k, v, self.mesh, axis=self.seq_axis, causal=True,
                dp_axis=AXIS_DATA, tp_axis=AXIS_MODEL,
            )
        else:
            from ..ops.ring_attention import dense_attention

            out = dense_attention(q, k, v, causal=True)
        return nn.DenseGeneral(
            features=cfg.d_model, axis=(-2, -1), use_bias=False, dtype=cfg.dtype, name="wo"
        )(out)


class MLP(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        gate = nn.Dense(cfg.d_ff, use_bias=False, dtype=cfg.dtype, name="w_gate")(x)
        up = nn.Dense(cfg.d_ff, use_bias=False, dtype=cfg.dtype, name="w_up")(x)
        return nn.Dense(cfg.d_model, use_bias=False, dtype=cfg.dtype, name="w_down")(
            nn.silu(gate) * up
        )


class Block(nn.Module):
    cfg: TransformerConfig
    mesh: Optional[Any] = None
    seq_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        x = x + Attention(cfg, self.mesh, self.seq_axis, name="attn")(
            RMSNorm(cfg.norm_eps, name="attn_norm")(x), positions
        )
        x = x + MLP(cfg, name="mlp")(RMSNorm(cfg.norm_eps, name="mlp_norm")(x))
        return x


class Transformer(nn.Module):
    cfg: TransformerConfig
    mesh: Optional[Any] = None
    seq_axis: Optional[str] = None

    @nn.compact
    def __call__(self, tokens, train: bool = True):
        cfg = self.cfg
        b, s = tokens.shape
        positions = jnp.broadcast_to(jnp.arange(s), (b, s))
        x = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype, name="embed")(tokens)
        block = Block
        if cfg.remat:
            policy = (
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable
                if cfg.remat_policy == "dots" else None
            )
            block = nn.remat(Block, static_argnums=(), policy=policy)
        for i in range(cfg.n_layers):
            x = block(cfg, self.mesh, self.seq_axis, name=f"layer_{i}")(x, positions)
        x = RMSNorm(cfg.norm_eps, name="final_norm")(x)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.logits_dtype, name="lm_head")(x)
        return logits
