"""CNN zoo breadth: MobileNet v1/v3, EfficientNet, VGG.

Parity targets from the reference model hub (``model/model_hub.py:66-73``:
``mobilenet`` -> ``model/cv/mobilenet.py``, ``mobilenet_v3`` ->
``mobilenet_v3.py``, ``efficientnet`` -> ``efficientnet.py``; VGG from
``model/cv/vgg.py``) re-derived in flax from the published architectures.

TPU notes: convs run in the configured compute dtype (bf16 by default via
model_hub) so the MXU sees bf16 systolic matmuls; normalization statistics
stay f32 inside flax's BatchNorm/GroupNorm.  Small-input datasets (CIFAR
32x32) use stride-1 stems — the standard CIFAR adaptation — selected by
``small_input``.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn


def _norm(norm: str, dtype, train: bool):
    """Norm factory; BatchNorm follows resnet.py's convention
    (use_running_average=not train — stats update during training, the
    batch_stats collection is mutable in the trainer)."""
    if norm == "group":
        return lambda name=None: nn.GroupNorm(num_groups=8, dtype=dtype, name=name)
    return lambda name=None: nn.BatchNorm(
        use_running_average=not train, momentum=0.9, dtype=dtype, name=name)


class DepthwiseSeparable(nn.Module):
    """MobileNetV1 block: 3x3 depthwise + 1x1 pointwise (Howard et al.)."""

    features: int
    stride: int
    norm: str
    dtype: Any

    @nn.compact
    def __call__(self, x, train: bool = True):
        make_norm = _norm(self.norm, self.dtype, train)
        c_in = x.shape[-1]
        x = nn.Conv(c_in, (3, 3), strides=self.stride, padding="SAME",
                    feature_group_count=c_in, use_bias=False, dtype=self.dtype)(x)
        x = make_norm()(x)
        x = nn.relu(x)
        x = nn.Conv(self.features, (1, 1), use_bias=False, dtype=self.dtype)(x)
        x = make_norm()(x)
        return nn.relu(x)


class MobileNetV1(nn.Module):
    """Reference ``model/cv/mobilenet.py`` (width 1.0)."""

    num_classes: int
    norm: str = "batch"
    dtype: Any = jnp.float32
    small_input: bool = True  # CIFAR stem

    @nn.compact
    def __call__(self, x, train: bool = True):
        make_norm = _norm(self.norm, self.dtype, train)
        x = x.astype(self.dtype)
        stem_stride = 1 if self.small_input else 2
        x = nn.Conv(32, (3, 3), strides=stem_stride, padding="SAME", use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(make_norm()(x))
        plan = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2)] \
            + [(512, 1)] * 5 + [(1024, 2), (1024, 1)]
        for feats, stride in plan:
            x = DepthwiseSeparable(feats, stride, self.norm, self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


class SqueezeExcite(nn.Module):
    reduce: int
    dtype: Any

    @nn.compact
    def __call__(self, x):
        s = jnp.mean(x, axis=(1, 2))
        s = nn.relu(nn.Dense(max(x.shape[-1] // self.reduce, 4), dtype=self.dtype)(s))
        s = nn.sigmoid(nn.Dense(x.shape[-1], dtype=self.dtype)(s))
        return x * s[:, None, None, :]


class MBConv(nn.Module):
    """Inverted residual with optional SE — the shared block of MobileNetV3
    and EfficientNet (Sandler et al. / Tan & Le)."""

    features: int
    expand: int
    kernel: int
    stride: int
    use_se: bool
    norm: str
    dtype: Any
    activation: str = "relu"  # "relu" | "hswish" | "swish"

    def _act(self, x):
        if self.activation == "hswish":
            return x * nn.relu6(x + 3.0) / 6.0
        if self.activation == "swish":
            return nn.swish(x)
        return nn.relu(x)

    @nn.compact
    def __call__(self, x, train: bool = True):
        make_norm = _norm(self.norm, self.dtype, train)
        c_in = x.shape[-1]
        h = x
        mid = c_in * self.expand
        if self.expand != 1:
            h = self._act(make_norm()(nn.Conv(mid, (1, 1), use_bias=False, dtype=self.dtype)(h)))
        h = nn.Conv(mid, (self.kernel, self.kernel), strides=self.stride, padding="SAME",
                    feature_group_count=mid, use_bias=False, dtype=self.dtype)(h)
        h = self._act(make_norm()(h))
        if self.use_se:
            h = SqueezeExcite(4, self.dtype)(h)
        h = make_norm()(nn.Conv(self.features, (1, 1), use_bias=False, dtype=self.dtype)(h))
        if self.stride == 1 and c_in == self.features:
            h = h + x
        return h


class MobileNetV3Small(nn.Module):
    """Reference ``model/cv/mobilenet_v3.py`` ('small' profile)."""

    num_classes: int
    norm: str = "batch"
    dtype: Any = jnp.float32
    small_input: bool = True

    @nn.compact
    def __call__(self, x, train: bool = True):
        make_norm = _norm(self.norm, self.dtype, train)
        x = x.astype(self.dtype)
        stem_stride = 1 if self.small_input else 2
        x = nn.Conv(16, (3, 3), strides=stem_stride, padding="SAME", use_bias=False, dtype=self.dtype)(x)
        x = make_norm()(x)
        x = x * nn.relu6(x + 3.0) / 6.0
        # (features, expand, kernel, stride, se, act)
        plan = [
            (16, 1, 3, 2, True, "relu"),
            (24, 4, 3, 2, False, "relu"),
            (24, 3, 3, 1, False, "relu"),
            (40, 3, 5, 2, True, "hswish"),
            (40, 3, 5, 1, True, "hswish"),
            (48, 3, 5, 1, True, "hswish"),
            (96, 6, 5, 2, True, "hswish"),
            (96, 6, 5, 1, True, "hswish"),
        ]
        for feats, expand, kernel, stride, se, act in plan:
            x = MBConv(feats, expand, kernel, stride, se, self.norm, self.dtype, act)(x, train)
        x = nn.Conv(576, (1, 1), use_bias=False, dtype=self.dtype)(x)
        x = make_norm()(x)
        x = x * nn.relu6(x + 3.0) / 6.0
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(1024, dtype=self.dtype)(x)
        x = x * nn.relu6(x + 3.0) / 6.0
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


class EfficientNetB0(nn.Module):
    """Reference ``model/cv/efficientnet.py`` (B0 profile, MBConv + SE +
    swish)."""

    num_classes: int
    norm: str = "batch"
    dtype: Any = jnp.float32
    small_input: bool = True

    @nn.compact
    def __call__(self, x, train: bool = True):
        make_norm = _norm(self.norm, self.dtype, train)
        x = x.astype(self.dtype)
        stem_stride = 1 if self.small_input else 2
        x = nn.swish(make_norm()(nn.Conv(32, (3, 3), strides=stem_stride, padding="SAME",
                                         use_bias=False, dtype=self.dtype)(x)))
        # (features, expand, kernel, stride, repeats)
        plan = [
            (16, 1, 3, 1, 1), (24, 6, 3, 2, 2), (40, 6, 5, 2, 2),
            (80, 6, 3, 2, 3), (112, 6, 5, 1, 3), (192, 6, 5, 2, 4), (320, 6, 3, 1, 1),
        ]
        for feats, expand, kernel, stride, repeats in plan:
            for r in range(repeats):
                x = MBConv(feats, expand, kernel, stride if r == 0 else 1, True,
                           self.norm, self.dtype, "swish")(x, train)
        x = nn.swish(make_norm()(nn.Conv(1280, (1, 1), use_bias=False, dtype=self.dtype)(x)))
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


_VGG_PLANS = {
    11: (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    16: (64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M", 512, 512, 512, "M"),
}


class VGG(nn.Module):
    """VGG-11/16 with norm (reference ``model/cv/vgg.py`` capability)."""

    num_classes: int
    depth: int = 11
    norm: str = "batch"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        make_norm = _norm(self.norm, self.dtype, train)
        x = x.astype(self.dtype)
        for step in _VGG_PLANS[self.depth]:
            if step == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(int(step), (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(x)
                x = nn.relu(make_norm()(x))
        x = jnp.mean(x, axis=(1, 2))  # adaptive pool -> classifier
        x = nn.relu(nn.Dense(512, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
