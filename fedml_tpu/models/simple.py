"""Small models: logistic regression, CNNs, MLP, GAN.

Parity targets from the reference model zoo (``model/model_hub.py:19`` dispatch):
- ``lr``        -> LogisticRegression (MNIST 784->10; ``model/linear/lr.py``)
- ``cnn``       -> FedAvg-paper CNN for FeMNIST/MNIST (``model/cv/cnn.py``)
- ``cnn_web``   / tag-prediction MLPs
- mnist GAN (``model/gan/``) for the FedGAN algorithm.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn


class LogisticRegression(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(self.num_classes)(x)


class FedAvgCNN(nn.Module):
    """The McMahan-et-al FedAvg CNN (2x conv5x5 + 2 dense), as the reference's
    ``CNN_DropOut`` (``model/cv/cnn.py``) used for FeMNIST/MNIST."""

    num_classes: int = 62
    only_digits: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        if x.ndim == 3:
            x = x[..., None]
        x = nn.Conv(32, (5, 5), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(512)(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        n_out = 10 if self.only_digits else self.num_classes
        return nn.Dense(n_out)(x)


class CifarCNN(nn.Module):
    """Simple CIFAR CNN (reference ``model/cv/cnn.py`` CNN_WEB / simple-cnn)."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(32, (3, 3), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes)(x)


class MLP(nn.Module):
    """Tag-prediction / stackoverflow_lr style MLP over sparse features."""

    hidden: int = 128
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.hidden)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes)(x)


class MnistGanGenerator(nn.Module):
    """MNIST GAN generator (reference ``model/gan/`` for FedGan)."""

    latent_dim: int = 100

    @nn.compact
    def __call__(self, z, train: bool = True):
        x = nn.Dense(256)(z)
        x = nn.leaky_relu(x, 0.2)
        x = nn.Dense(512)(x)
        x = nn.leaky_relu(x, 0.2)
        x = nn.Dense(784)(x)
        return jnp.tanh(x).reshape((-1, 28, 28, 1))


class MnistGanDiscriminator(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(512)(x)
        x = nn.leaky_relu(x, 0.2)
        x = nn.Dense(256)(x)
        x = nn.leaky_relu(x, 0.2)
        return nn.Dense(1)(x)
