"""Compact UNet for FedSeg (reference ``simulation/mpi/fedseg`` trains
DeepLabV3+/UNet on pascal-style data; ``utils.py:56`` tracks accuracy /
per-class accuracy / mIoU / FWIoU)."""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn


class _ConvBlock(nn.Module):
    features: int
    dtype: Any

    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.GroupNorm(num_groups=4, dtype=self.dtype)(
            nn.Conv(self.features, (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(x)))
        x = nn.relu(nn.GroupNorm(num_groups=4, dtype=self.dtype)(
            nn.Conv(self.features, (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(x)))
        return x


class UNet(nn.Module):
    """2-level UNet: per-pixel class logits (B, H, W, num_classes)."""

    num_classes: int
    base: int = 16
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        d1 = _ConvBlock(self.base, self.dtype)(x)
        p1 = nn.max_pool(d1, (2, 2), strides=(2, 2))
        d2 = _ConvBlock(self.base * 2, self.dtype)(p1)
        p2 = nn.max_pool(d2, (2, 2), strides=(2, 2))
        mid = _ConvBlock(self.base * 4, self.dtype)(p2)
        u2 = nn.ConvTranspose(self.base * 2, (2, 2), strides=(2, 2), dtype=self.dtype)(mid)
        u2 = _ConvBlock(self.base * 2, self.dtype)(jnp.concatenate([u2, d2], axis=-1))
        u1 = nn.ConvTranspose(self.base, (2, 2), strides=(2, 2), dtype=self.dtype)(u2)
        u1 = _ConvBlock(self.base, self.dtype)(jnp.concatenate([u1, d1], axis=-1))
        return nn.Conv(self.num_classes, (1, 1), dtype=jnp.float32)(u1)


def segmentation_metrics(logits, labels, num_classes: int):
    """pixel accuracy, mIoU, FWIoU — reference ``EvaluationMetricsKeeper``
    (fedseg/utils.py:56) computed from the confusion matrix."""
    preds = jnp.argmax(logits, axis=-1).reshape(-1)
    labels = labels.reshape(-1)
    conf = jnp.zeros((num_classes, num_classes), jnp.float32).at[labels, preds].add(1.0)
    tp = jnp.diag(conf)
    union = conf.sum(0) + conf.sum(1) - tp
    iou = tp / jnp.maximum(union, 1.0)
    present = (conf.sum(1) > 0).astype(jnp.float32)
    freq = conf.sum(1) / jnp.maximum(conf.sum(), 1.0)
    return {
        "pixel_acc": tp.sum() / jnp.maximum(conf.sum(), 1.0),
        "miou": (iou * present).sum() / jnp.maximum(present.sum(), 1.0),
        "fwiou": (freq * iou).sum(),
    }
