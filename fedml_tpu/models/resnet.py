"""CIFAR ResNets (resnet20/32/44/56) in flax.linen.

Capability parity with the reference's torch CIFAR ResNet family
(``model/cv/resnet.py`` — resnet20/32/44/56, 3 stages of widths 16/32/64,
option-A identity shortcuts) and its BN-free GroupNorm variant
(``model/cv/resnet_gn.py``), which the reference carries precisely because
BatchNorm statistics are ill-posed under federated averaging (SURVEY.md §7
hard part 3).

TPU notes: NHWC layout (XLA-native), bf16-friendly conv/matmul, static shapes
throughout.  BatchNorm running stats live in the ``batch_stats`` collection and
are treated as part of the federated state (averaged with the same weights as
parameters, matching FedAvg-on-state_dict in the reference, which averages BN
buffers too — ``fedavg_api.py:144-159`` iterates all state_dict keys).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops.pallas.fused_block import fused_bn_relu, fused_bn_residual_relu


class BasicBlock(nn.Module):
    filters: int
    stride: int = 1
    norm: str = "batch"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        residual = x
        y = nn.Conv(self.filters, (3, 3), strides=(self.stride, self.stride), padding="SAME", use_bias=False, dtype=self.dtype)(x)
        y = _norm_layer(self.norm, train, self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(y)
        y = _norm_layer(self.norm, train, self.dtype)(y)
        if residual.shape != y.shape:
            # Option-A shortcut (parameter-free, as in the reference's
            # LambdaLayer pad shortcut): stride-subsample + zero-pad channels.
            residual = residual[:, :: self.stride, :: self.stride, :]
            pad = self.filters - residual.shape[-1]
            residual = jnp.pad(residual, ((0, 0), (0, 0), (0, 0), (pad // 2, pad - pad // 2)))
        return nn.relu(y + residual)


def _norm_layer(norm: str, train: bool, dtype=jnp.float32):
    if norm == "group":
        return nn.GroupNorm(num_groups=2, dtype=dtype)
    return nn.BatchNorm(use_running_average=not train, momentum=0.9, epsilon=1e-5, dtype=dtype)


class _FusedBNScaleShift(nn.Module):
    """BatchNorm stats/params with ``nn.BatchNorm``'s exact variable layout
    (params ``scale``/``bias``, batch_stats ``mean``/``var``, f32, momentum
    0.9, eps 1e-5, fast variance), returning the folded per-channel affine
    ``(scale, shift)`` with ``normalized = x * scale + shift`` instead of
    normalizing — the application itself is the fused Pallas epilogue's job.

    Instantiated with an explicit ``name="BatchNorm_k"`` so the fused model's
    variable tree is IDENTICAL (names, shapes, init values) to the unfused
    one: checkpoints, FedAvg state averaging, and the A/B bench all interop.

    Gradients flow through mean/var into the conv output exactly as in
    ``nn.BatchNorm`` — the folding is plain jnp, so autodiff chains the
    kernel's d(scale)/d(shift) cotangents back through rsqrt and the batch
    reductions (which XLA fuses into the producing conv; see PERF.md).
    """

    use_running_average: bool
    momentum: float = 0.9
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x):
        features = x.shape[-1]
        ra_mean = self.variable(
            "batch_stats", "mean", lambda s: jnp.zeros(s, jnp.float32), (features,)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda s: jnp.ones(s, jnp.float32), (features,)
        )
        gamma = self.param("scale", nn.initializers.ones, (features,), jnp.float32)
        beta = self.param("bias", nn.initializers.zeros, (features,), jnp.float32)
        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            xf = x.astype(jnp.float32)
            axes = tuple(range(x.ndim - 1))
            mean = jnp.mean(xf, axes)
            # fast variance (flax _compute_stats): E[x^2] - E[x]^2, clipped
            var = jnp.maximum(0.0, jnp.mean(jnp.square(xf), axes) - jnp.square(mean))
            if not self.is_initializing():
                ra_mean.value = self.momentum * ra_mean.value + (1.0 - self.momentum) * mean
                ra_var.value = self.momentum * ra_var.value + (1.0 - self.momentum) * var
        scale = gamma * jax.lax.rsqrt(var + self.epsilon)
        return scale, beta - mean * scale


class FusedBasicBlock(nn.Module):
    """``BasicBlock`` with both conv epilogues (BN apply, shortcut add, ReLU)
    executed by the fused Pallas kernel (``ops/pallas/fused_block.py``) —
    one VMEM-resident HBM pass each instead of XLA's separate loop fusions.
    Same parameter/state tree as ``BasicBlock`` (child modules carry the
    auto-generated names of the unfused variant).  BatchNorm only; the
    GroupNorm escape hatch keeps the unfused block."""

    filters: int
    stride: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        residual = x
        y = nn.Conv(self.filters, (3, 3), strides=(self.stride, self.stride), padding="SAME", use_bias=False, dtype=self.dtype)(x)
        s1, b1 = _FusedBNScaleShift(use_running_average=not train, name="BatchNorm_0")(y)
        y = fused_bn_relu(y, s1, b1)
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(y)
        s2, b2 = _FusedBNScaleShift(use_running_average=not train, name="BatchNorm_1")(y)
        if residual.shape != y.shape:
            residual = residual[:, :: self.stride, :: self.stride, :]
            pad = self.filters - residual.shape[-1]
            residual = jnp.pad(residual, ((0, 0), (0, 0), (0, 0), (pad // 2, pad - pad // 2)))
        return fused_bn_residual_relu(y, s2, b2, residual)


class CifarResNet(nn.Module):
    """3-stage CIFAR ResNet; depth = 6n+2.

    ``fused=True`` (the ``hp/extra.fused_blocks`` recipe flag) routes every
    conv epilogue — stem BN+ReLU and both BasicBlock epilogues — through the
    fused Pallas kernel; BatchNorm only.  The variable tree is identical to
    the unfused model (explicit child names), so the two are checkpoint- and
    aggregation-compatible.  The default (``fused=False``) path is untouched.
    """

    num_blocks: int  # n per stage
    num_classes: int = 10
    norm: str = "batch"
    dtype: Any = jnp.float32
    fused: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        fused = self.fused and self.norm == "batch"
        x = x.astype(self.dtype)
        x = nn.Conv(16, (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(x)
        if fused:
            s, b = _FusedBNScaleShift(use_running_average=not train, name="BatchNorm_0")(x)
            x = fused_bn_relu(x, s, b)
        else:
            x = _norm_layer(self.norm, train, self.dtype)(x)
            x = nn.relu(x)
        idx = 0
        for stage, filters in enumerate((16, 32, 64)):
            for block in range(self.num_blocks):
                stride = 2 if (stage > 0 and block == 0) else 1
                if fused:
                    # explicit name keeps the tree identical to the unfused
                    # model's auto-numbered BasicBlock_{idx}
                    x = FusedBasicBlock(filters, stride, self.dtype,
                                        name=f"BasicBlock_{idx}")(x, train=train)
                else:
                    x = BasicBlock(filters, stride, self.norm, self.dtype)(x, train=train)
                idx += 1
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x


def resnet20(num_classes: int = 10, norm: str = "batch", dtype=jnp.float32, fused: bool = False) -> CifarResNet:
    return CifarResNet(num_blocks=3, num_classes=num_classes, norm=norm, dtype=dtype, fused=fused)


def resnet32(num_classes: int = 10, norm: str = "batch", dtype=jnp.float32, fused: bool = False) -> CifarResNet:
    return CifarResNet(num_blocks=5, num_classes=num_classes, norm=norm, dtype=dtype, fused=fused)


def resnet44(num_classes: int = 10, norm: str = "batch", dtype=jnp.float32, fused: bool = False) -> CifarResNet:
    return CifarResNet(num_blocks=7, num_classes=num_classes, norm=norm, dtype=dtype, fused=fused)


def resnet56(num_classes: int = 10, norm: str = "batch", dtype=jnp.float32, fused: bool = False) -> CifarResNet:
    return CifarResNet(num_blocks=9, num_classes=num_classes, norm=norm, dtype=dtype, fused=fused)


class SplitResNet56Client(nn.Module):
    """Client half of the split resnet56 (reference ``model/cv/resnet56/``:
    client owns conv stem + first stage; server owns the rest).  Used by
    FedGKT / SplitNN (P7/P8)."""

    norm: str = "batch"

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(16, (3, 3), padding="SAME", use_bias=False)(x)
        x = _norm_layer(self.norm, train)(x)
        x = nn.relu(x)
        for block in range(9):
            x = BasicBlock(16, 1, self.norm)(x, train=train)
        return x  # feature map handed to the server half


class SplitResNet56Server(nn.Module):
    num_classes: int = 10
    norm: str = "batch"

    @nn.compact
    def __call__(self, x, train: bool = True):
        for stage, filters in enumerate((32, 64)):
            for block in range(9):
                stride = 2 if block == 0 else 1
                x = BasicBlock(filters, stride, self.norm)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)
