"""CIFAR ResNets (resnet20/32/44/56) in flax.linen.

Capability parity with the reference's torch CIFAR ResNet family
(``model/cv/resnet.py`` — resnet20/32/44/56, 3 stages of widths 16/32/64,
option-A identity shortcuts) and its BN-free GroupNorm variant
(``model/cv/resnet_gn.py``), which the reference carries precisely because
BatchNorm statistics are ill-posed under federated averaging (SURVEY.md §7
hard part 3).

TPU notes: NHWC layout (XLA-native), bf16-friendly conv/matmul, static shapes
throughout.  BatchNorm running stats live in the ``batch_stats`` collection and
are treated as part of the federated state (averaged with the same weights as
parameters, matching FedAvg-on-state_dict in the reference, which averages BN
buffers too — ``fedavg_api.py:144-159`` iterates all state_dict keys).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import jax.numpy as jnp
from flax import linen as nn


class BasicBlock(nn.Module):
    filters: int
    stride: int = 1
    norm: str = "batch"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        residual = x
        y = nn.Conv(self.filters, (3, 3), strides=(self.stride, self.stride), padding="SAME", use_bias=False, dtype=self.dtype)(x)
        y = _norm_layer(self.norm, train, self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(y)
        y = _norm_layer(self.norm, train, self.dtype)(y)
        if residual.shape != y.shape:
            # Option-A shortcut (parameter-free, as in the reference's
            # LambdaLayer pad shortcut): stride-subsample + zero-pad channels.
            residual = residual[:, :: self.stride, :: self.stride, :]
            pad = self.filters - residual.shape[-1]
            residual = jnp.pad(residual, ((0, 0), (0, 0), (0, 0), (pad // 2, pad - pad // 2)))
        return nn.relu(y + residual)


def _norm_layer(norm: str, train: bool, dtype=jnp.float32):
    if norm == "group":
        return nn.GroupNorm(num_groups=2, dtype=dtype)
    return nn.BatchNorm(use_running_average=not train, momentum=0.9, epsilon=1e-5, dtype=dtype)


class CifarResNet(nn.Module):
    """3-stage CIFAR ResNet; depth = 6n+2."""

    num_blocks: int  # n per stage
    num_classes: int = 10
    norm: str = "batch"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(16, (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(x)
        x = _norm_layer(self.norm, train, self.dtype)(x)
        x = nn.relu(x)
        for stage, filters in enumerate((16, 32, 64)):
            for block in range(self.num_blocks):
                stride = 2 if (stage > 0 and block == 0) else 1
                x = BasicBlock(filters, stride, self.norm, self.dtype)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x


def resnet20(num_classes: int = 10, norm: str = "batch", dtype=jnp.float32) -> CifarResNet:
    return CifarResNet(num_blocks=3, num_classes=num_classes, norm=norm, dtype=dtype)


def resnet32(num_classes: int = 10, norm: str = "batch", dtype=jnp.float32) -> CifarResNet:
    return CifarResNet(num_blocks=5, num_classes=num_classes, norm=norm, dtype=dtype)


def resnet44(num_classes: int = 10, norm: str = "batch", dtype=jnp.float32) -> CifarResNet:
    return CifarResNet(num_blocks=7, num_classes=num_classes, norm=norm, dtype=dtype)


def resnet56(num_classes: int = 10, norm: str = "batch", dtype=jnp.float32) -> CifarResNet:
    return CifarResNet(num_blocks=9, num_classes=num_classes, norm=norm, dtype=dtype)


class SplitResNet56Client(nn.Module):
    """Client half of the split resnet56 (reference ``model/cv/resnet56/``:
    client owns conv stem + first stage; server owns the rest).  Used by
    FedGKT / SplitNN (P7/P8)."""

    norm: str = "batch"

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(16, (3, 3), padding="SAME", use_bias=False)(x)
        x = _norm_layer(self.norm, train)(x)
        x = nn.relu(x)
        for block in range(9):
            x = BasicBlock(16, 1, self.norm)(x, train=train)
        return x  # feature map handed to the server half


class SplitResNet56Server(nn.Module):
    num_classes: int = 10
    norm: str = "batch"

    @nn.compact
    def __call__(self, x, train: bool = True):
        for stage, filters in enumerate((32, 64)):
            for block in range(9):
                stride = 2 if block == 0 else 1
                x = BasicBlock(filters, stride, self.norm)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)
