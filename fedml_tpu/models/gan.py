"""GAN pair for FedGAN (reference ``model/gan/`` + ``simulation/mpi/fedgan/
gan_trainer.py:11`` — netd/netg trained per client, both aggregated)."""

from __future__ import annotations

from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn


class Generator(nn.Module):
    """z -> flat image in [-1, 1] (MLP-DCGAN hybrid scaled for 28x28/32x32)."""

    out_shape: Sequence[int] = (28, 28, 1)
    z_dim: int = 64
    hidden: int = 256
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, z, train: bool = True):
        out_dim = 1
        for d in self.out_shape:
            out_dim *= d
        h = nn.relu(nn.Dense(self.hidden, dtype=self.dtype)(z))
        h = nn.relu(nn.Dense(self.hidden, dtype=self.dtype)(h))
        x = nn.tanh(nn.Dense(out_dim, dtype=jnp.float32)(h))
        return x.reshape((z.shape[0],) + tuple(self.out_shape))


class Discriminator(nn.Module):
    """image -> real/fake logit."""

    hidden: int = 256
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        h = x.reshape((x.shape[0], -1)).astype(self.dtype)
        h = nn.leaky_relu(nn.Dense(self.hidden, dtype=self.dtype)(h), 0.2)
        h = nn.leaky_relu(nn.Dense(self.hidden // 2, dtype=self.dtype)(h), 0.2)
        return nn.Dense(1, dtype=jnp.float32)(h)[:, 0]
