"""UnitedLLM — cross-cloud federated LLM training over the wire.

Parity with ``spotlight_prj/unitedllm/run_unitedllm.py`` (the workload the
reference's cross-cloud "Cheetah" platform exists to host): silos in
different clouds fine-tune a shared LLM on private corpora and exchange ONLY
LoRA adapter trees through the cross-silo protocol — the frozen base model
never crosses the network (the reference ships PEFT adapter state-dicts the
same way, ``spotlight_prj/fedllm/src/fedllm_trainer.py``).

Composition, not duplication: the silo trainer implements the
``FedMLTrainer`` train() contract, the aggregator subclasses
``FedMLAggregator`` with the LoRA tree as its global state, and both plug
into the UNCHANGED cross-silo server/client managers — so every transport
(INPROC/TCP/gRPC/MQTT), the straggler handling, and the finish protocol work
for LLM silos for free.  The base model is derived deterministically from
``random_seed`` on every party (in a real deployment each cloud loads the
same public checkpoint; what matters is only the adapters ride the wire).

A round moves O(rank * d * layers) floats per silo.  For the default tiny
config that is ~100x smaller than the base model — asserted by test.

The exchange rides every cross-silo fast path (ISSUE 12):

- **Streaming/associative folds**: ``LoRAAggregator`` opts into the
  ``supports_associative_fold`` protocol via ``_init_stream_mode``, so
  adapter uploads fold leaf-by-leaf into the streaming accumulator (peak
  buffered <= 2) on both the sync server and the buffered-async server
  (staleness-decayed LoRA folding, FedBuff-style).  A configured trust
  pipeline still forces the exact buffer-all path — the PR-4 gate.
- **Compressed delta uploads**: behind ``extra.comm_compression`` the silo
  ships the qsgd8/topk-compressed DELTA vs the received global adapters.
  Rank-r factors are small, so the trainer declares a per-tree
  ``comm_compress_min_elems`` override (``codecs.LOW_RANK_MIN_COMPRESS_
  ELEMS``) that lets adapter-sized leaves compress where the model-scale
  default would leave them raw; an explicit ``comm_compress_min_size`` flag
  still wins.
- **Sharded server folds**: behind ``extra.server_shard_fold`` the fold and
  the finalized adapter tree go through ``parallel/mesh`` NamedShardings —
  folded on the shard-owning devices under jit, never host-gathered.
"""

from __future__ import annotations

import logging
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..core import rng
from ..core.flags import cfg_extra
from ..cross_silo.client import ClientMasterManager
from ..cross_silo.server import FedMLAggregator, FedMLServerManager
from ..models.transformer import Transformer, TransformerConfig
from . import lora as lora_lib

log = logging.getLogger("fedml_tpu.llm.unitedllm")


def _build_base(cfg, dataset):
    """Deterministic (cfg.random_seed-keyed) frozen base model shared by all
    parties — the stand-in for 'every cloud loads the same checkpoint'."""
    tcfg = TransformerConfig.tiny(vocab_size=dataset.class_num)
    model = Transformer(tcfg)
    k0 = rng.root_key(cfg.random_seed)
    sample = jnp.zeros((cfg.batch_size, dataset.train_x.shape[1]), jnp.int32)
    base_params = model.init({"params": jax.random.fold_in(k0, 1)}, sample)["params"]
    lora0 = lora_lib.init_lora(
        base_params, int(cfg_extra(cfg, "lora_r", 4)), jax.random.fold_in(k0, 2),
        targets=cfg_extra(cfg, "lora_targets", lora_lib.DEFAULT_TARGETS),
    )
    alpha = float(cfg_extra(cfg, "lora_alpha"))
    return model, base_params, lora0, alpha


class LoRASiloTrainer:
    """``FedMLTrainer``-shaped local operator: global state is the LoRA tree;
    the base stays frozen and silo-resident."""

    def __init__(self, cfg, dataset, x: np.ndarray, y: np.ndarray):
        self.cfg = cfg
        self.model, self.base_params, _, self.alpha = _build_base(cfg, dataset)
        # batches are drawn by random index in [0, count) — no padding needed
        self.x = jnp.asarray(x)
        self.y = jnp.asarray(y)
        self.count = jnp.int32(x.shape[0])
        self._steps = cfg.epochs * max(1, math.ceil(x.shape[0] / cfg.batch_size))
        self._train = jax.jit(self._make_step())
        # per-tree compression floor: rank-r adapter factors are far below
        # the model-scale comm_compress_min_size default, so the exchanged
        # tree would ship raw; this override (picked up by the client
        # manager unless the flag is set explicitly) lets every
        # non-expanding adapter leaf ride the qsgd8/topk wire
        from ..comm.codecs import LOW_RANK_MIN_COMPRESS_ELEMS

        self.comm_compress_min_elems = LOW_RANK_MIN_COMPRESS_ELEMS

    def _make_step(self):
        cfg = self.cfg
        opt = optax.adamw(cfg.learning_rate)
        model, base, alpha = self.model, self.base_params, self.alpha

        def loss_fn(lora, x, y):
            params = lora_lib.merge(base, lora, alpha=alpha)
            logits = model.apply({"params": params}, x, train=True)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), y
            ).mean()

        grad_fn = jax.value_and_grad(loss_fn)
        steps = self._steps

        def run(lora, x, y, count, key):
            opt_state = opt.init(lora)

            def step(carry, s):
                lora, opt_state = carry
                idx = jax.random.randint(jax.random.fold_in(key, s), (cfg.batch_size,), 0, count)
                loss, g = grad_fn(lora, jnp.take(x, idx, 0), jnp.take(y, idx, 0))
                u, opt_state = opt.update(g, opt_state, lora)
                return (optax.apply_updates(lora, u), opt_state), loss

            (lora, _), losses = jax.lax.scan(step, (lora, opt_state), jnp.arange(steps))
            return lora, jnp.mean(losses)

        return run

    def train(self, global_lora, round_idx: int, seed_key, client_idx: int = 0) -> tuple:
        key = rng.client_key(rng.round_key(seed_key, round_idx), client_idx)
        lora = jax.tree_util.tree_map(jnp.asarray, global_lora)
        new_lora, loss = self._train(lora, self.x, self.y, self.count, key)
        log.info("silo %d round %d lora train loss %.4f", client_idx, round_idx, float(loss))
        return jax.device_get(new_lora), float(self.count)


class LoRAAggregator(FedMLAggregator):
    """Cross-silo aggregator whose global state is the LoRA tree; evaluation
    merges base+adapters and reports LM loss/perplexity.

    On the associative-fold protocol: adapter aggregation is the stock
    sample-weighted mean, so with compression/streaming/async flags set and
    no trust pipeline configured, uploads fold leaf-by-leaf into the
    streaming accumulator exactly like vision models (``_init_stream_mode``
    applies the same gate — ``trust is None`` included, so secure-agg/FHE/DP
    trust configurations still force the exact buffer-all path)."""

    def __init__(self, cfg, dataset, trust=None):
        # deliberately NOT calling super().__init__: the base class builds a
        # classifier + eval pipeline from a flax vision model; here global
        # state is the adapter tree and eval is LM loss
        self.cfg = cfg
        self.model, self.base_params, self.global_vars, self.alpha = _build_base(cfg, dataset)
        from ..algorithms import create as create_algorithm, hparams_from_config
        from ..cross_silo.server import provisional_steps_per_epoch

        self.hp = hparams_from_config(cfg, steps_per_epoch=provisional_steps_per_epoch(cfg))
        self.algorithm = create_algorithm(cfg, self.hp)  # aggregate/server_update only
        self.server_state = self.algorithm.init_server_state(self.global_vars)
        if trust is None:
            from ..trust.pipeline import build_trust_pipeline

            trust = build_trust_pipeline(cfg)
        self.trust = trust
        self._schedule_calibrated = True  # adapters carry no schedule state
        self.root_key = rng.root_key(cfg.random_seed)
        self.model_dict: dict[int, object] = {}
        self.sample_num_dict: dict[int, float] = {}
        self.flag_client_model_uploaded: dict[int, bool] = {}
        n_eval = min(256, len(dataset.test_x))
        self._eval_x = jnp.asarray(dataset.test_x[:n_eval])
        self._eval_y = jnp.asarray(dataset.test_y[:n_eval])
        self._eval_jit = jax.jit(self._eval_loss)
        # no AOT-stored programs for the adapter eval (tiny trees, cheap jit)
        self._aot = None
        self._program_items: list = []
        # the PR-4 streaming gate, shared with the base class: folds engage
        # only behind the flags AND with no trust pipeline configured
        self._init_stream_mode(cfg)

    def _calibrate_schedule(self) -> None:  # adapters: nothing to calibrate
        return

    def _eval_loss(self, lora, x, y):
        params = lora_lib.merge(self.base_params, lora, alpha=self.alpha)
        logits = self.model.apply({"params": params}, x, train=False)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), y
        ).mean()
        return {"test_loss": loss, "test_ppl": jnp.exp(loss)}

    def test_on_server(self) -> dict:
        res = self._eval_jit(self.global_vars, self._eval_x, self._eval_y)
        return {k: float(v) for k, v in res.items()}


def build_unitedllm_server(cfg, dataset, backend: Optional[str] = None) -> FedMLServerManager:
    aggregator = LoRAAggregator(cfg, dataset)
    if cfg_extra(cfg, "async_aggregation"):
        # buffered-async LoRA: silo uploads fold with staleness-decayed
        # weights, virtual rounds close at async_buffer_k arrivals — the
        # same manager the vision path uses, adapter tree as global state
        from ..cross_silo.async_server import AsyncFedMLServerManager

        return AsyncFedMLServerManager(cfg, aggregator, backend=backend)
    return FedMLServerManager(cfg, aggregator, backend=backend)


def build_unitedllm_client(cfg, dataset, rank: int, backend: Optional[str] = None) -> ClientMasterManager:
    ix = dataset.client_idx[rank - 1]
    trainer = LoRASiloTrainer(cfg, dataset, dataset.train_x[ix], dataset.train_y[ix])
    return ClientMasterManager(cfg, trainer, rank=rank, backend=backend)


def run_unitedllm_process_group(cfg, dataset, backend: str = "INPROC", timeout: float = 600.0):
    """1 server + N LLM silos on threads — over INPROC or real TCP loopback
    (the reference smoke runs its silos as background processes over MQTT;
    TCP is this build's routable equivalent)."""
    if backend == "INPROC":
        from ..comm.inproc import InProcRouter

        InProcRouter.reset(str(getattr(cfg, "run_id", "0")))
    # the server is constructed FIRST so its transport listener exists before
    # any client's first status send (real sockets, unlike the buffering
    # in-proc router, refuse connections to an unbound port)
    server = build_unitedllm_server(cfg, dataset, backend=backend)
    clients = [
        build_unitedllm_client(cfg, dataset, rank=r, backend=backend)
        for r in range(1, cfg.client_num_in_total + 1)
    ]
    for c in clients:
        c.run_in_thread()
    try:
        history = server.run_until_done(timeout=timeout)
        # graceful drain: a buffered-async silo may still be mid-train on its
        # daemon thread when the server finishes — give each a bounded window
        # to process FINISH, so interpreter exit never lands mid-XLA-call
        for c in clients:
            c.done.wait(5.0)
    finally:
        for c in clients:
            c.finish()
    return history, server
