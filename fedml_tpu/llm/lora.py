"""LoRA — low-rank adaptation as a pure parameter transform.

Parity with the reference's PEFT integration (``train/llm/configurations.py``
``ModelArguments`` LoRA r/alpha/dropout/target fields :181-188; FedLLM
exchanges only the PEFT state dict).  Here LoRA is functional: adapters are a
separate pytree ``{path: {"a": (in, r), "b": (r, out)}}`` and

    merged = base + (alpha / r) * reshape(a @ b)

is differentiable w.r.t. the adapters, so ``jax.grad`` of
``loss(merge(base, lora))`` trains ONLY the adapters with the base frozen —
no model surgery, works for any flax model.  The federated payload is the
adapter tree alone (the whole point of FedLLM: exchange K entries of rank-r
factors, not 7B weights).
"""

from __future__ import annotations

import re
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_TARGETS = r".*attn/w[qkvo]/kernel"


def _match_paths(params, targets: str):
    out = []

    def visit(path, leaf):
        ps = "/".join(str(getattr(p, "key", p)) for p in path)
        if re.fullmatch(targets, ps) and leaf.ndim >= 2:
            out.append((ps, leaf.shape, leaf.dtype))

    jax.tree_util.tree_map_with_path(visit, params)
    return out


def init_lora(params, rank: int, key: jax.Array, targets: str = DEFAULT_TARGETS,
              dtype=jnp.float32) -> dict:
    """Adapter tree keyed by 'path/with/slashes' -> {a, b}."""
    lora = {}
    for i, (path, shape, _) in enumerate(_match_paths(params, targets)):
        d_in = shape[0]
        d_out = int(np.prod(shape[1:]))
        ka = jax.random.fold_in(key, 2 * i)
        lora[path] = {
            "a": jax.random.normal(ka, (d_in, rank), dtype) * (1.0 / max(1, d_in)) ** 0.5,
            "b": jnp.zeros((rank, d_out), dtype),  # zero init: merge starts as identity
        }
    if not lora:
        raise ValueError(f"no parameters matched LoRA targets {targets!r}")
    return lora


def merge(base_params, lora: dict, alpha: float = 16.0, rank: Optional[int] = None):
    """base + (alpha/r) * a@b, reshaped to each target's shape.  Pure and
    differentiable in ``lora``."""
    if rank is None:
        rank = next(iter(lora.values()))["a"].shape[1]
    scale = alpha / rank

    def update(path, leaf):
        ps = "/".join(str(getattr(p, "key", p)) for p in path)
        ab = lora.get(ps)
        if ab is None:
            return leaf
        delta = (ab["a"] @ ab["b"]).reshape(leaf.shape) * scale
        return leaf + delta.astype(leaf.dtype)

    return jax.tree_util.tree_map_with_path(update, base_params)


def lora_size(lora: dict) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(lora))
