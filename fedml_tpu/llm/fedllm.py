"""FedLLM — federated LoRA fine-tuning.

Parity with ``spotlight_prj/fedllm`` (``run_fedllm.py:47``,
``src/fedllm_trainer.py``): each silo fine-tunes LoRA adapters on its local
corpus; only the adapter tree (PEFT state-dict equivalent) crosses the
network; the server sample-weight-averages adapters.  The base model stays
frozen and device-resident — a round moves O(rank * d * layers) floats, not
the model.

The local step trains adapters through ``merge(base, lora)`` (see
``llm/lora.py``); the whole client update is one jitted scan, and adapter
averaging is the same ``tree_weighted_mean`` as every other algorithm.
"""

from __future__ import annotations

import math
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..arguments import Config
from ..core import pytree as pt, rng
from ..core.flags import cfg_extra
from ..models.transformer import Transformer, TransformerConfig
from ..obs.metrics import MetricsLogger
from . import lora as lora_lib


from ..core.checkpoint import RoundCheckpointMixin


class FedLLMSimulator(RoundCheckpointMixin):
    """Federated LoRA over token-sequence clients.

    dataset: FederatedDataset whose train_x are token sequences (b, T) and
    train_y the shifted targets (see data.loader text path).
    """

    def __init__(self, cfg: Config, dataset, tcfg: Optional[TransformerConfig] = None):
        self.cfg = cfg
        self.dataset = dataset
        self.rank = int(cfg_extra(cfg, "lora_r", 8))
        self.alpha = float(cfg_extra(cfg, "lora_alpha"))
        self.tcfg = tcfg or TransformerConfig.tiny(vocab_size=dataset.class_num)
        self.model = Transformer(self.tcfg)
        k0 = rng.root_key(cfg.random_seed)
        sample = jnp.zeros((cfg.batch_size, dataset.train_x.shape[1]), jnp.int32)
        self.base_params = self.model.init({"params": jax.random.fold_in(k0, 1)}, sample)["params"]
        self.global_lora = lora_lib.init_lora(
            self.base_params, self.rank, jax.random.fold_in(k0, 2),
            targets=cfg_extra(cfg, "lora_targets", lora_lib.DEFAULT_TARGETS),
        )
        self.root_key = k0
        self.round_idx = 0
        self.logger = MetricsLogger(cfg.metrics_jsonl_path or None)
        self._client_step = jax.jit(self._make_client_step())
        self._eval = jax.jit(self._eval_loss)

    def _make_client_step(self):
        cfg = self.cfg
        model = self.model
        alpha = self.alpha
        opt = optax.adamw(cfg.learning_rate)

        def loss_fn(lora, x, y):
            params = lora_lib.merge(self.base_params, lora, alpha=alpha)
            logits = model.apply({"params": params}, x, train=True)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), y
            ).mean()

        grad_fn = jax.value_and_grad(loss_fn)

        # one static step budget for all clients (shards are padded to a
        # common capacity, so there is exactly ONE compilation, not one per
        # distinct shard size); batches sample uniformly over the true count
        counts = self.dataset.local_sample_counts()
        self._capacity = int(counts.max())
        steps = cfg.epochs * max(1, self._capacity // cfg.batch_size)

        def client_step(lora, x, y, count, key):
            opt_state = opt.init(lora)

            def step(carry, s):
                lora, opt_state = carry
                idx = jax.random.randint(
                    jax.random.fold_in(key, s), (cfg.batch_size,), 0, count
                )
                loss, g = grad_fn(lora, jnp.take(x, idx, 0), jnp.take(y, idx, 0))
                u, opt_state = opt.update(g, opt_state, lora)
                return (optax.apply_updates(lora, u), opt_state), loss

            (lora, _), losses = jax.lax.scan(step, (lora, opt_state), jnp.arange(steps))
            return lora, jnp.mean(losses)

        return client_step

    def _eval_loss(self, lora, x, y):
        params = lora_lib.merge(self.base_params, lora, alpha=self.alpha)
        logits = self.model.apply({"params": params}, x, train=False)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), y
        ).mean()
        return {"test_loss": loss, "test_ppl": jnp.exp(loss)}

    def run_round(self) -> dict:
        cfg = self.cfg
        ds = self.dataset
        n_total = ds.n_clients
        m = min(cfg.client_num_per_round, n_total)
        sampled = np.asarray(rng.sample_clients(self.root_key, self.round_idx, n_total, m))
        rkey = rng.round_key(self.root_key, self.round_idx)
        loras, weights, losses = [], [], []
        for ci in sampled:
            ix = ds.client_idx[int(ci)]
            reps = np.resize(ix, self._capacity)  # pad to the shared capacity
            x = jnp.asarray(ds.train_x[reps])
            y = jnp.asarray(ds.train_y[reps])
            new_lora, loss = self._client_step(
                self.global_lora, x, y, jnp.int32(len(ix)), rng.client_key(rkey, int(ci))
            )
            loras.append(new_lora)
            weights.append(float(len(ix)))
            losses.append(float(loss))
        stacked = pt.tree_stack(loras)
        self.global_lora = pt.tree_weighted_mean(stacked, jnp.asarray(weights))
        self.round_idx += 1
        return {"train_loss": float(np.mean(losses))}

    def evaluate(self, max_samples: int = 256) -> dict:
        ds = self.dataset
        x = jnp.asarray(ds.test_x[:max_samples])
        y = jnp.asarray(ds.test_y[:max_samples])
        return {k: float(v) for k, v in self._eval(self.global_lora, x, y).items()}

    # -- round-level checkpoint/resume (reference FedLLM PauseResumeCallback,
    # spotlight_prj/fedllm/src/trainer_callback.py: each FL round resumes the
    # trainer at a step offset; here the adapter tree + RNG are the state) ---
    def _ckpt_state(self) -> dict:
        return {
            "global_lora": self.global_lora,
            "round_idx": self.round_idx,
            "root_key": self.root_key,
        }

    def _apply_ckpt_state(self, state: dict) -> None:
        self.global_lora = jax.tree_util.tree_map(jnp.asarray, state["global_lora"])
        self.round_idx = int(state["round_idx"])
        # checkpointed key is authoritative (same contract as MeshSimulator)
        self.root_key = jnp.asarray(state["root_key"])

    def run(self) -> list[dict]:
        history = []
        self.try_resume()
        while self.round_idx < self.cfg.comm_round:
            r = self.round_idx
            t0 = time.perf_counter()
            metrics = self.run_round()
            metrics.update(round=r, round_time_s=time.perf_counter() - t0)
            if self.cfg.frequency_of_the_test and (
                (r + 1) % self.cfg.frequency_of_the_test == 0 or r == self.cfg.comm_round - 1
            ):
                metrics.update(self.evaluate())
            self.logger.log(metrics)
            history.append(metrics)
            self.maybe_save_checkpoint(r)
        return history
