"""LLM trainer — pjit-sharded next-token training.

Capability target: the reference's ``train/llm`` stack (HF Trainer +
DeepSpeed ZeRO-3 + bf16, ``hf_trainer.py``, ``distributed.py:21-68``) and the
TensorOpera-Train "Llama-3 distributed pretrain" config (BASELINE.md).
TPU-native: one jitted train step over a (data, model, seq) mesh — ZeRO-3 is
the parameter sharding rules (``parallel/sharding.py``), tensor parallelism
is the model axis, ring attention the seq axis; AdamW + cosine schedule +
grad clipping mirror the reference's TrainingArguments defaults; perplexity
logging matches ``hf_trainer.py``'s metric.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..core import rng
from ..models.transformer import Transformer, TransformerConfig
from ..obs.metrics import MetricsLogger
from ..parallel import mesh as meshlib, sharding


@dataclass(frozen=True)
class LLMTrainArgs:
    """Reference ``ExperimentArguments(TrainingArguments)`` essentials
    (``train/llm/configurations.py:32``)."""

    learning_rate: float = 3e-4
    warmup_steps: int = 100
    total_steps: int = 1000
    weight_decay: float = 0.1
    grad_clip: float = 1.0
    batch_size: int = 8
    seq_len: int = 512
    seed: int = 0


class LLMTrainer:
    def __init__(self, cfg: TransformerConfig, args: LLMTrainArgs,
                 mesh=None, seq_axis: Optional[str] = None,
                 logger: Optional[MetricsLogger] = None):
        self.cfg = cfg
        self.args = args
        if mesh is None:
            mesh = meshlib.make_mesh((meshlib.AXIS_DATA,))
        self.mesh = mesh
        self.seq_axis = seq_axis if (seq_axis and seq_axis in mesh.shape and mesh.shape[seq_axis] > 1) else None
        self.model = Transformer(cfg, mesh=mesh if self.seq_axis else None, seq_axis=self.seq_axis)
        self.logger = logger or MetricsLogger()

        k0 = rng.root_key(args.seed)
        sample = jnp.zeros((args.batch_size, args.seq_len), jnp.int32)
        with jax.default_device(jax.devices("cpu")[0] if jax.default_backend() != "cpu" else jax.devices()[0]):
            variables = jax.eval_shape(lambda: self.model.init({"params": k0}, sample))
        # materialize params directly into their shardings (no host spike)
        self.param_shardings = sharding.named_shardings(variables["params"], mesh)

        def init_fn():
            return self.model.init({"params": k0}, sample)["params"]

        self.params = jax.jit(
            init_fn, out_shardings=self.param_shardings
        )()

        schedule = optax.warmup_cosine_decay_schedule(
            0.0, args.learning_rate, args.warmup_steps, max(args.total_steps, args.warmup_steps + 1)
        )
        self.opt = optax.chain(
            optax.clip_by_global_norm(args.grad_clip),
            optax.adamw(schedule, b1=0.9, b2=0.95, weight_decay=args.weight_decay),
        )
        # Optimizer moments must NOT inherit shardings by propagation: optax
        # init builds them as zeros with no data dependence on the params, so
        # XLA places them on device 0 (SingleDeviceSharding) — a multi-device
        # step then rejects the mixed device set.  The moment paths end with
        # the param path ('...nu/layer_0/attn/wq/kernel'), so the same
        # path-regex rules shard them like their params; scalars (count)
        # fall through to the replicate-by-default rule.
        opt_shardings = sharding.named_shardings(
            jax.eval_shape(self.opt.init, self.params), mesh
        )
        self.opt_state = jax.jit(self.opt.init, out_shardings=opt_shardings)(self.params)
        self.data_sharding = sharding.batch_sharding(mesh, seq_axis=self.seq_axis)
        self.step_idx = 0
        # Pin the step's output shardings to the input shardings: with
        # donation and unspecified out_shardings, XLA may pick different
        # layouts for the outputs, and the SECOND call then recompiles
        # against the new input layouts (a silent ~80 s hit on real chips).
        scalar_sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
        self._train_step = jax.jit(
            self._make_train_step(),
            donate_argnums=(0, 1),
            out_shardings=(self.param_shardings, opt_shardings,
                           {"loss": scalar_sh, "ppl": scalar_sh}),
        )

    def _make_train_step(self):
        model = self.model
        opt = self.opt

        def loss_fn(params, tokens, targets):
            logits = model.apply({"params": params}, tokens, train=True)
            losses = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), targets
            )
            return losses.mean()

        def train_step(params, opt_state, tokens, targets):
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, {"loss": loss, "ppl": jnp.exp(loss)}

        return train_step

    def step(self, tokens: jax.Array, targets: jax.Array) -> dict:
        tokens = jax.device_put(tokens, self.data_sharding)
        targets = jax.device_put(targets, self.data_sharding)
        self.params, self.opt_state, metrics = self._train_step(
            self.params, self.opt_state, tokens, targets
        )
        self.step_idx += 1
        return {k: float(v) for k, v in metrics.items()}

    def fit(self, batch_iter, steps: Optional[int] = None) -> list[dict]:
        history = []
        steps = steps or self.args.total_steps
        for i, (tokens, targets) in enumerate(batch_iter):
            if i >= steps:
                break
            t0 = time.perf_counter()
            m = self.step(tokens, targets)
            m["step"] = self.step_idx
            m["step_time_s"] = time.perf_counter() - t0
            self.logger.log(m)
            history.append(m)
        return history

    def n_params(self) -> int:
        return sum(x.size for x in jax.tree_util.tree_leaves(self.params))

    def token_throughput(self, steps: int = 5) -> float:
        """tokens/sec on synthetic data (bench helper).

        Two warmup steps (first compile + any layout settle), then ``steps``
        back-to-back device steps with a single host sync at the end — the
        per-step host round trip would otherwise dominate on tunneled chips.
        """
        a = self.args
        key = jax.random.PRNGKey(0)
        tokens = jax.random.randint(key, (a.batch_size, a.seq_len), 0, self.cfg.vocab_size)
        targets = jnp.roll(tokens, -1, axis=1)
        tokens = jax.device_put(tokens, self.data_sharding)
        targets = jax.device_put(targets, self.data_sharding)
        params, opt_state = self.params, self.opt_state
        for _ in range(2):  # warmup: compile + layout settle
            params, opt_state, m = self._train_step(params, opt_state, tokens, targets)
            float(m["loss"])
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, m = self._train_step(params, opt_state, tokens, targets)
        float(m["loss"])  # host sync
        dt = time.perf_counter() - t0
        self.params, self.opt_state = params, opt_state
        return a.batch_size * a.seq_len * steps / dt
