"""Continuous micro-batcher in front of the jitted predictor (ISSUE 11).

Today's serving stack executes one padded jit call per HTTP request: N
concurrent requests mean N dispatches of a ``max_batch``-lane program each
carrying one real row — lane utilization 1/max_batch and device queueing
delay proportional to the request count.  This module is the standard
continuous-batching fix (the inference-side analogue of the PR-4 streaming
accumulator: overlap arrival with compute, never wait for a full set):

- **Bounded admission queue.**  ``submit`` either enqueues the request or
  raises :class:`QueueOverflow` — the HTTP layer maps it to 503 +
  ``Retry-After`` — so a traffic spike degrades to explicit backpressure,
  never unbounded queue growth / OOM.
- **Coalesce, dispatch as soon as the device frees.**  One dispatcher
  thread drains the queue into the fixed padded batch lanes the predictor
  already compiles for and runs ONE program per micro-batch.  A lone
  request never waits for a full batch: the loop dispatches whatever is
  queued the moment the previous batch returns, and an optional
  ``flush_ms`` window only delays a PARTIAL batch long enough for arrivals
  already in flight to join (0 = dispatch immediately).
- **Per-request futures** carry queue/execute/total latency into the
  ``fedml_serving_*`` histograms (p50/p99 come from the bucket counts),
  plus QPS and batch-fill-fraction gauges — the numbers the autoscaler and
  the serving bench read.
- **Hot-swap seam.**  The predictor for each micro-batch is resolved
  per-dispatch through an optional route controller
  (:class:`~fedml_tpu.serving.publisher.HotSwapController`), so a version
  swap lands between micro-batches with zero dropped in-flight requests:
  the executing batch keeps the predictor object it started with, the next
  batch sees the new one.

Thread model (GL008-audited): request threads call ``submit``/``stats``,
the dispatcher thread drains; every shared mutable touch runs under the
one queue ``Condition``.  Predictor execution runs OUTSIDE the lock so a
slow program never blocks admission.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Optional

import numpy as np

from ..obs import registry as obsreg

__all__ = ["MicroBatcher", "QueueOverflow", "BatchRequest"]

QUEUE_TIME = obsreg.REGISTRY.histogram(
    "fedml_serving_queue_seconds",
    "Admission-queue wait per request (submit to micro-batch dispatch).",
)
EXECUTE_TIME = obsreg.REGISTRY.histogram(
    "fedml_serving_execute_seconds",
    "Predictor execution wall time per micro-batch.",
)
REQUEST_TIME = obsreg.REGISTRY.histogram(
    "fedml_serving_request_seconds",
    "Total in-batcher latency per request (submit to result ready).",
)
REQUESTS = obsreg.REGISTRY.counter(
    "fedml_serving_requests_total",
    "Requests through the micro-batcher, by outcome (ok / rejected = 503 "
    "backpressure / error = batch execution failure).",
    labels=("outcome",),
)
BATCHES = obsreg.REGISTRY.counter(
    "fedml_serving_batches_total",
    "Micro-batches dispatched to the predictor.",
)
BATCH_FILL = obsreg.REGISTRY.gauge(
    "fedml_serving_batch_fill_fraction",
    "EWMA fraction of padded batch lanes carrying real rows per dispatch.",
)
QPS_GAUGE = obsreg.REGISTRY.gauge(
    "fedml_serving_qps",
    "EWMA requests/s completed by the micro-batcher.",
)
QUEUE_DEPTH = obsreg.REGISTRY.gauge(
    "fedml_serving_queue_depth",
    "Requests waiting in the admission queue.",
)


class QueueOverflow(RuntimeError):
    """Admission queue full: the caller should answer 503 and retry after
    ``retry_after_s`` (an estimate of when a lane frees up)."""

    def __init__(self, depth: int, retry_after_s: float):
        super().__init__(f"admission queue full ({depth} requests waiting)")
        self.depth = depth
        self.retry_after_s = retry_after_s


class BatchRequest:
    """One submitted request: rows in, a waitable result out (the future the
    HTTP handler blocks on)."""

    __slots__ = ("x", "n", "submit_t", "done", "outputs", "error", "version",
                 "queue_s", "execute_s", "total_s")

    def __init__(self, x: np.ndarray):
        self.x = x
        self.n = int(x.shape[0])
        self.submit_t = time.monotonic()
        self.done = threading.Event()
        self.outputs: Optional[np.ndarray] = None
        self.error: Optional[Exception] = None
        self.version: Optional[int] = None
        self.queue_s = 0.0
        self.execute_s = 0.0
        self.total_s = 0.0

    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self.done.wait(timeout):
            raise TimeoutError("micro-batch result not ready in time")
        if self.error is not None:
            raise self.error
        return self.outputs


class MicroBatcher:
    """Continuous micro-batcher (see module docstring).

    ``controller`` is the hot-swap seam: an object with
    ``route() -> (predictor, version, is_canary)`` and
    ``observe_batch(version, ok, execute_s, is_canary, fallback)``;
    ``None`` pins the constructor predictor forever (plain serving).
    """

    def __init__(self, predictor, *, controller=None, max_batch: Optional[int] = None,
                 max_queue: int = 256, flush_ms: float = 2.0):
        self._predictor = predictor
        self._controller = controller
        self.max_batch = int(max_batch or getattr(predictor, "max_batch", 32))
        self.max_queue = int(max_queue)
        self.flush_s = max(0.0, float(flush_ms) / 1000.0)
        # one Condition is both the admission mutex and the dispatcher's
        # wakeup — a single lock identity for every shared-state access
        self._cond = threading.Condition()
        self._queue: list[BatchRequest] = []
        self._stopped = False
        # accounting (guarded by _cond)
        self._completed = 0
        self._rejected = 0
        self._errored = 0
        self._batches = 0
        self._fill_ewma: Optional[float] = None
        self._qps_ewma: Optional[float] = None
        self._batch_s_ewma: Optional[float] = None
        self._last_dispatch_t: Optional[float] = None
        self._thread = threading.Thread(target=self._dispatch_loop,
                                        name="fedml-serving-batcher", daemon=True)
        self._thread.start()

    # -- request side ---------------------------------------------------------
    def submit(self, x) -> BatchRequest:
        """Enqueue one request of ``rows x features...``; raises
        :class:`QueueOverflow` when the admission queue is full and
        ``ValueError`` for rows that can never fit the compiled lanes."""
        x = np.asarray(x, dtype=np.float32)
        if x.ndim < 2:
            x = x.reshape(1, -1)
        if x.shape[0] > self.max_batch:
            raise ValueError(
                f"request batch {x.shape[0]} exceeds max_batch {self.max_batch}")
        req = BatchRequest(x)
        with self._cond:
            if self._stopped:
                raise RuntimeError("micro-batcher stopped")
            if len(self._queue) + 1 > self.max_queue:
                self._rejected += 1
                REQUESTS.inc(outcome="rejected")
                raise QueueOverflow(len(self._queue), self._retry_after_locked())
            self._queue.append(req)
            QUEUE_DEPTH.set(len(self._queue))
            self._cond.notify()
        return req

    def _retry_after_locked(self) -> float:
        """Backpressure hint: roughly how long until the queued backlog has
        drained one max_batch worth of lanes."""
        per_batch = self._batch_s_ewma or 0.05
        backlog_batches = max(1.0, len(self._queue) / max(1, self.max_batch))
        return max(0.05, per_batch * backlog_batches)

    @property
    def retry_after_s(self) -> float:
        with self._cond:
            return self._retry_after_locked()

    # -- dispatcher -----------------------------------------------------------
    def _take_batch_locked(self) -> list[BatchRequest]:
        batch: list[BatchRequest] = []
        lanes = 0
        while self._queue and lanes + self._queue[0].n <= self.max_batch:
            req = self._queue.pop(0)
            batch.append(req)
            lanes += req.n
        QUEUE_DEPTH.set(len(self._queue))
        return batch

    def _dispatch_loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stopped:
                    self._cond.wait(timeout=0.1)
                if self._stopped and not self._queue:
                    return
                first_t = self._queue[0].submit_t
                # flush window: hold a PARTIAL batch open only until the
                # oldest request has waited flush_s (arrivals already in
                # flight get to join); a full batch never waits
                if self.flush_s > 0:
                    deadline = first_t + self.flush_s
                    while (sum(r.n for r in self._queue) < self.max_batch
                           and not self._stopped):
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        self._cond.wait(timeout=remaining)
                batch = self._take_batch_locked()
            if batch:
                self._execute(batch)

    def _execute(self, batch: list[BatchRequest]) -> None:
        now = time.monotonic()
        for req in batch:
            req.queue_s = now - req.submit_t
            QUEUE_TIME.observe(req.queue_s)
        xs = np.concatenate([req.x for req in batch]) if len(batch) > 1 else batch[0].x
        if self._controller is not None:
            pred, version, is_canary = self._controller.route()
        else:
            pred, version, is_canary = self._predictor, None, False
        served_version = version
        t0 = time.monotonic()
        outputs, err, regressed = self._run(pred, xs)
        fallback = False
        if is_canary and (err is not None or regressed):
            # canary regression (exception OR non-finite outputs) must not
            # cost the requests: re-execute on the stable predictor; the
            # controller records the regression against the canary version
            pred, served_version, _ = self._controller.stable()
            outputs, err, _ = self._run(pred, xs)
            fallback = True
        execute_s = time.monotonic() - t0
        EXECUTE_TIME.observe(execute_s)
        if self._controller is not None:
            self._controller.observe_batch(
                version, err is None, execute_s, is_canary, fallback)
        done_t = time.monotonic()
        off = 0
        for req in batch:
            req.execute_s = execute_s
            req.total_s = done_t - req.submit_t
            req.version = served_version
            if err is None:
                req.outputs = outputs[off:off + req.n]
            else:
                req.error = err
            off += req.n
            REQUEST_TIME.observe(req.total_s)
            REQUESTS.inc(outcome="ok" if err is None else "error")
            req.done.set()
        self._account(batch, err, done_t)

    def _run(self, pred, xs):
        """(outputs, error, canary_regressed): non-finite canary output is a
        regression exactly like an exception — a poisoned published tree
        must never be promoted on latency alone."""
        try:
            out = np.asarray(pred.predict_rows(xs))
        except Exception as e:  # the batch fails together; callers see the error
            return None, e, True
        if not np.all(np.isfinite(out)):
            return out, None, True
        return out, None, False

    def _account(self, batch: list[BatchRequest], err, done_t: float) -> None:
        rows = sum(r.n for r in batch)
        fill = rows / max(1, self.max_batch)
        with self._cond:
            self._batches += 1
            if err is None:
                self._completed += len(batch)
            else:
                self._errored += len(batch)
            self._fill_ewma = (fill if self._fill_ewma is None
                               else 0.3 * fill + 0.7 * self._fill_ewma)
            exec_s = batch[0].execute_s
            self._batch_s_ewma = (exec_s if self._batch_s_ewma is None
                                  else 0.3 * exec_s + 0.7 * self._batch_s_ewma)
            if self._last_dispatch_t is not None:
                dt = max(1e-6, done_t - self._last_dispatch_t)
                rate = len(batch) / dt
                self._qps_ewma = (rate if self._qps_ewma is None
                                  else 0.3 * rate + 0.7 * self._qps_ewma)
            self._last_dispatch_t = done_t
            fill_ewma, qps_ewma = self._fill_ewma, self._qps_ewma
        BATCHES.inc()
        BATCH_FILL.set(fill_ewma)
        if qps_ewma is not None:
            QPS_GAUGE.set(qps_ewma)

    # -- lifecycle / stats ----------------------------------------------------
    def stats(self) -> dict:
        with self._cond:
            return {
                "completed": self._completed,
                "rejected": self._rejected,
                "errored": self._errored,
                "batches": self._batches,
                "queue_depth": len(self._queue),
                "batch_fill_ewma": (round(self._fill_ewma, 4)
                                    if self._fill_ewma is not None else None),
                "qps_ewma": (round(self._qps_ewma, 2)
                             if self._qps_ewma is not None else None),
                "max_batch": self.max_batch,
                "max_queue": self.max_queue,
            }

    def stop(self, drain_timeout_s: float = 5.0) -> None:
        """Stop accepting work; the dispatcher drains what is queued (every
        accepted request resolves — shutdown must not drop in-flight work)."""
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout=max(0.1, drain_timeout_s))


def percentile_from_histogram(hist, q: float) -> Optional[float]:
    """Approximate quantile (upper bucket bound at the cumulative crossing)
    from a registry histogram — how the bench reads p50/p99 out of the
    ``fedml_serving_*`` families."""
    snap = hist._snapshot()
    if not snap["samples"]:
        return None
    counts = snap["samples"][0]["counts"]
    total = sum(counts)
    if total <= 0:
        return None
    target = math.ceil(q * total)
    cum = 0
    for bound, n in zip(snap["buckets"], counts):
        cum += n
        if cum >= target:
            return float(bound)
    return float(snap["buckets"][-1])
