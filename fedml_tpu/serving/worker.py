"""Inference replica worker — one process per replica.

The process the deploy scheduler spawns (reference: the per-replica inference
container started by ``device_model_deployment.py:start_deployment``; here a
plain process, container-free by design).  Loads a model-hub model + a
pytree-wire parameter file and serves predict/ready over HTTP
(``serving/inference.py``).

Usage: python -m fedml_tpu.serving.worker --model lr --classes 10 \
           --params /path/params.wire --port 2500 [--feature-dim 32]
"""

from __future__ import annotations

import argparse
import sys


def load_params(path: str):
    from ..comm import wire

    with open(path, "rb") as f:
        return wire.decode_pytree(f.read())


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", required=True)
    ap.add_argument("--classes", type=int, default=10)
    ap.add_argument("--params", required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--max-batch", type=int, default=32)
    args = ap.parse_args(argv)

    from ..arguments import Config
    from ..models import model_hub
    from .inference import FedMLInferenceRunner, JaxPredictor

    cfg = Config(model=args.model, dataset="synthetic")
    model = model_hub.create(cfg, args.classes)
    variables = load_params(args.params)
    predictor = JaxPredictor(model, variables, max_batch=args.max_batch)
    # Warm up BEFORE serving: readiness must mean "can answer within SLO",
    # and the first jit compile can take tens of seconds on a loaded host —
    # a /ready that predates compilation makes the gateway time out.
    feat_shape = _infer_feature_shape(variables)
    if feat_shape is not None:
        predictor.predict({"inputs": [[0.0] * feat_shape[0]]})
    runner = FedMLInferenceRunner(predictor, host=args.host, port=args.port)
    runner.run(block=True)
    return 0


def _infer_feature_shape(variables):
    """Best-effort input shape from the first kernel leaf (LR/MLP: (d, c) ->
    (d,)); None when unknown (conv models warm up on first request)."""
    import numpy as np

    def walk(tree):
        if isinstance(tree, dict):
            for k, v in tree.items():
                if k == "kernel" and getattr(v, "ndim", 0) == 2:
                    return (int(np.asarray(v).shape[0]),)
                got = walk(v)
                if got is not None:
                    return got
        return None

    return walk(variables)


if __name__ == "__main__":
    sys.exit(main())
