"""Inference replica worker — one process (or in-process object) per replica.

The process the deploy scheduler spawns (reference: the per-replica inference
container started by ``device_model_deployment.py:start_deployment``; here a
plain process, container-free by design).  Loads a model-hub model + a
pytree-wire parameter file and serves predict/ready over HTTP
(``serving/inference.py``) through the continuous micro-batcher
(``serving/batcher.py``).

ISSUE 11 makes the worker a **continuous-serving** replica:

- requests coalesce into the fixed padded batch lanes (bounded admission,
  503 + Retry-After on overflow);
- with ``--publish-dir`` the worker polls the training server's publication
  manifest (``serving/publisher.py``) and hot-swaps the parameter tree
  between micro-batches — zero dropped in-flight requests, optional
  canary-fraction routing with auto-rollback on a health regression;
- with ``--aot-dir`` the inference apply resolves through the AOT program
  store, so a restarted worker deserializes in milliseconds and ``/ready``
  means "compiled and warm";
- ``--feature-dim`` names the input feature shape (e.g. ``32`` for LR/MLP,
  ``32,32,3`` for conv models) so warmup/AOT work even when the shape is
  not inferable from the parameter tree.

Usage: python -m fedml_tpu.serving.worker --model lr --classes 10 \
           --params /path/params.wire --port 2500 [--feature-dim 32] \
           [--publish-dir /path/pub] [--canary-fraction 0.1] [--aot-dir D]
"""

from __future__ import annotations

import argparse
import logging
import sys
import threading
from typing import Optional

log = logging.getLogger("fedml_tpu.serving.worker")


def load_params(path: str):
    from ..comm import wire

    with open(path, "rb") as f:
        return wire.decode_pytree(f.read())


def parse_feature_dim(spec: Optional[str]):
    """``"32"`` -> ``(32,)``; ``"32,32,3"`` -> ``(32, 32, 3)``; None/""
    -> None (fall back to :func:`_infer_feature_shape`)."""
    if not spec:
        return None
    return tuple(int(d) for d in str(spec).split(",") if str(d).strip())


class ServingWorker:
    """One serving replica as a library object: model + batcher + HTTP
    runner + (optional) manifest watcher/hot-swap/canary.  The CLI ``main``
    below and the serving bench/dryrun both drive this class; tests use it
    in-process."""

    def __init__(self, model_name: str, classes: int, *,
                 params=None, params_path: Optional[str] = None,
                 publish_dir: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 max_batch: int = 32, max_queue: int = 256,
                 flush_ms: float = 2.0, canary_fraction: float = 0.0,
                 canary_min_batches: int = 8, poll_s: float = 0.05,
                 feature_shape=None, aot_dir: Optional[str] = None,
                 bootstrap_timeout_s: float = 60.0,
                 flight_dir: Optional[str] = None,
                 eval_batch=None):
        from ..arguments import Config
        from ..models import model_hub
        from .batcher import MicroBatcher
        from .inference import FedMLInferenceRunner, JaxPredictor
        from .publisher import HotSwapController, ManifestWatcher, watch_and_swap

        cfg = Config(model=model_name, dataset="synthetic")
        self.model = model_hub.create(cfg, int(classes))
        self.publish_dir = publish_dir
        self._watcher: Optional[ManifestWatcher] = None
        self._stop = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None
        self.flight = None
        if flight_dir:
            from ..obs.flight import FlightRecorder

            self.flight = FlightRecorder(
                str(flight_dir), name="serving",
                meta={"role": "serving", "model": model_name})

        version = 0
        if params is None and params_path:
            params = load_params(params_path)
        if params is None:
            if not publish_dir:
                raise ValueError(
                    "worker needs --params or --publish-dir (manifest bootstrap)")
            # bootstrap from the publication manifest: serve the first
            # published version without any local artifact
            boot = ManifestWatcher(publish_dir)
            got = boot.wait_for_version(0, timeout_s=bootstrap_timeout_s,
                                        poll_s=min(0.05, poll_s))
            if got is None:
                raise TimeoutError(
                    f"no model published under {publish_dir} within "
                    f"{bootstrap_timeout_s}s")
            version, path, _manifest = got
            params = load_params(path)
            self._watcher = boot
        elif publish_dir:
            self._watcher = ManifestWatcher(publish_dir, last_version=version)

        aot_store = None
        if aot_dir:
            from ..core.aot import ProgramStore

            aot_store = ProgramStore(str(aot_dir))
        if feature_shape is None:
            feature_shape = _infer_feature_shape(params)
        self.predictor = JaxPredictor(
            self.model, params, max_batch=max_batch, aot_store=aot_store,
            feature_shape=feature_shape, model_name=model_name)
        # Warm up BEFORE serving: readiness must mean "can answer within
        # SLO", and the first jit compile can take tens of seconds on a
        # loaded host — a /ready that predates compilation makes the
        # gateway time out.  (With --aot-dir the warm is a deserialized
        # program's first execution: milliseconds.)
        self.predictor.warm()
        # optional labeled eval batch (x, y): canaries are scored on real
        # held-out accuracy before promotion — see HotSwapController
        self.swap = HotSwapController(
            self.predictor, version=version,
            canary_fraction=canary_fraction,
            canary_min_batches=canary_min_batches,
            eval_batch=eval_batch)
        self.batcher = MicroBatcher(
            self.predictor, controller=self.swap, max_batch=max_batch,
            max_queue=max_queue, flush_ms=flush_ms)
        self.runner = FedMLInferenceRunner(
            self.predictor, host=host, port=port, batcher=self.batcher,
            stats_fn=self.stats)
        if self._watcher is not None:
            self._watch_thread = watch_and_swap(
                self._watcher, self.swap, self._load_version, self._stop,
                poll_s=poll_s)
        if self.flight is not None:
            self.flight.note("serving_boot", version=version,
                             canary_fraction=canary_fraction,
                             aot=bool(aot_dir), publish_dir=bool(publish_dir))

    # -- hot swap -------------------------------------------------------------
    def _load_version(self, version: int, path: str, _manifest: dict):
        """Decode + warm a published tree OFF the serving path (the old tree
        serves until this returns): the zero-drop half of the hot swap."""
        params = load_params(path)
        pred = self.predictor.clone_with(params)
        pred.warm()
        if self.flight is not None:
            # versions the watcher hands us; whether each one PROMOTED or
            # rolled back shows up in the stop-dump's swap stats
            self.flight.note("swap", version=int(version),
                             prev=int(self.swap.version))
            self.flight.record_metric_deltas()
        return pred

    # -- lifecycle ------------------------------------------------------------
    def start(self, block: bool = False) -> int:
        """Serve; returns the bound port (non-blocking mode)."""
        return self.runner.run(block=block)

    def stop(self) -> None:
        self._stop.set()
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=5.0)
        self.batcher.stop()
        self.runner.stop()
        if self.flight is not None:
            self.flight.record_metric_deltas()
            self.flight.trigger("serving_stop", stats=self.stats(),
                                version=int(self.swap.version))
            self.flight.close()

    def stats(self) -> dict:
        return {**self.batcher.stats(), **self.swap.stats()}

    @property
    def served_version(self) -> int:
        return self.swap.version


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", required=True)
    ap.add_argument("--classes", type=int, default=10)
    ap.add_argument("--params", default=None,
                    help="pytree-wire params file (optional with --publish-dir)")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--max-queue", type=int, default=256,
                    help="admission queue bound (full queue -> 503 + Retry-After)")
    ap.add_argument("--flush-ms", type=float, default=2.0,
                    help="partial micro-batch flush deadline (0 = immediate)")
    ap.add_argument("--feature-dim", default=None,
                    help="input feature shape, comma-separated (e.g. 32 or "
                         "32,32,3) — overrides inference from the parameter "
                         "tree so conv models warm up before /ready too")
    ap.add_argument("--publish-dir", default=None,
                    help="training server's model publication dir: poll the "
                         "manifest and hot-swap new versions with zero "
                         "dropped requests")
    ap.add_argument("--poll-s", type=float, default=0.25,
                    help="manifest poll interval")
    ap.add_argument("--canary-fraction", type=float, default=0.0,
                    help="fraction of micro-batches routed to a freshly "
                         "published version before promotion (0 = direct "
                         "swap); regressions auto-roll-back")
    ap.add_argument("--canary-min-batches", type=int, default=8)
    ap.add_argument("--aot-dir", default=None,
                    help="AOT program store dir: deserialize the exported "
                         "inference apply instead of re-tracing on restart")
    ap.add_argument("--flight-dir", default=None,
                    help="flight-recorder bundle dir: record swaps/rollbacks "
                         "and dump a black box on SIGTERM, crash, or stop")
    args = ap.parse_args(argv)

    worker = ServingWorker(
        args.model, args.classes, params_path=args.params,
        publish_dir=args.publish_dir, host=args.host, port=args.port,
        max_batch=args.max_batch, max_queue=args.max_queue,
        flush_ms=args.flush_ms, canary_fraction=args.canary_fraction,
        canary_min_batches=args.canary_min_batches, poll_s=args.poll_s,
        feature_shape=parse_feature_dim(args.feature_dim),
        aot_dir=args.aot_dir, flight_dir=args.flight_dir)
    if worker.flight is not None:
        # one replica per process: the process-wide SIGTERM/excepthook taps
        # are this worker's to take
        worker.flight.install_signal_handlers()
    worker.start(block=True)
    return 0


def _infer_feature_shape(variables):
    """Best-effort input shape from the first kernel leaf (LR/MLP: (d, c) ->
    (d,)); None when unknown (conv models need ``--feature-dim`` to warm up
    before serving)."""
    import numpy as np

    def walk(tree):
        if isinstance(tree, dict):
            for k, v in tree.items():
                if k == "kernel" and getattr(v, "ndim", 0) == 2:
                    return (int(np.asarray(v).shape[0]),)
                got = walk(v)
                if got is not None:
                    return got
        return None

    return walk(variables)


if __name__ == "__main__":
    sys.exit(main())
