"""Model serving: predictor interface + HTTP inference runner.

Parity with ``serving/fedml_predictor.py:4`` (user subclasses
``FedMLPredictor`` with ``predict``/``ready``) and
``serving/fedml_inference_runner.py:8`` (``FedMLInferenceRunner`` wraps it in
``POST /predict`` + ``GET /ready``).  The reference uses FastAPI; this build
serves the same routes from the stdlib ThreadingHTTPServer (FastAPI is not in
the image), so the client-side contract — JSON in, JSON out, 200/503 ready
semantics — is identical.

TPU notes: ``JaxPredictor`` jits the model apply once and pads request
batches to a fixed size so serving never retraces per request shape.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

import numpy as np


class FedMLPredictor:
    """Reference API shape (``fedml_predictor.py``)."""

    def predict(self, request: dict) -> Any:
        raise NotImplementedError

    def predict_stream(self, request: dict):
        """Streaming response: an iterator of JSON-serializable chunks
        (reference ``fedml_inference_runner.py:20-27`` wraps the predictor's
        generator in a ``StreamingResponse`` when the request sets
        ``stream``).  Default: one chunk, the plain prediction."""
        yield self.predict(request)

    def predict_file(self, request: dict, accept: str) -> str:
        """Non-JSON Accept header: return a path to a file to serve
        (reference ``fedml_inference_runner.py:34-36`` wraps the predictor
        result in a ``FileResponse``).  Predictors producing binary artifacts
        (images, audio, model files) override this."""
        raise NotImplementedError(
            f"this predictor produces JSON only (Accept: {accept!r})"
        )

    def ready(self) -> bool:
        return True


class JaxPredictor(FedMLPredictor):
    """Serve a flax model: request {"inputs": [[...], ...]} -> {"outputs": ...}.

    Pads every batch to ``max_batch`` so one compiled program serves all
    request sizes (no per-shape retrace).

    **AOT-warm restarts** (ISSUE 11): with ``aot_store`` (a
    ``core.aot.ProgramStore``) and a known ``feature_shape``, the apply is
    resolved through the program store — a restarted worker DESERIALIZES
    the exported StableHLO in milliseconds instead of re-tracing, and the
    eager bind compiles it at construction, so ``/ready`` means "compiled
    and warm", not "process up".  Store miss/unavailable falls back to the
    plain ``jax.jit`` path (never a crash).

    **Hot swap**: :meth:`clone_with` builds a predictor for a NEW parameter
    tree that SHARES this one's compiled apply (the program is keyed by
    tree structure, not values), so a version swap pays one warm execution,
    zero compiles.
    """

    def __init__(self, model, variables, max_batch: int = 32,
                 aot_store=None, feature_shape=None, model_name: str = ""):
        import jax
        import jax.numpy as jnp

        self.model = model
        self.variables = variables
        self.max_batch = int(max_batch)
        self.model_name = model_name
        self.feature_shape = (tuple(feature_shape)
                              if feature_shape is not None else None)
        apply_fn = lambda v, x: model.apply(v, x, train=False)  # noqa: E731
        self._apply = None
        if aot_store is not None and self.feature_shape is not None:
            from ..core import aot as aotlib

            example = (variables,
                       jnp.zeros((self.max_batch,) + self.feature_shape,
                                 jnp.float32))
            key = aotlib.program_key(
                "serving.predict",
                trees={"args": example},
                hparams={"max_batch": self.max_batch},
                extra={"model": model_name or type(model).__name__})
            # eager=True: the bind AOT-compiles now, so readiness == warm
            self._apply = aot_store.cached_jit(
                apply_fn, example, key=key, eager=True)
        if self._apply is None:
            self._apply = jax.jit(apply_fn)
        self._jnp = jnp

    def clone_with(self, variables) -> "JaxPredictor":
        """A predictor over ``variables`` sharing this one's compiled apply
        (the hot-swap path: no store lookup, no re-trace, no compile)."""
        clone = type(self).__new__(type(self))
        clone.model = self.model
        clone.variables = variables
        clone.max_batch = self.max_batch
        clone.model_name = self.model_name
        clone.feature_shape = self.feature_shape
        clone._apply = self._apply
        clone._jnp = self._jnp
        return clone

    def warm(self) -> None:
        """One padded execution so the first real request never pays the
        compile (and a swapped-in tree never serves cold)."""
        if self.feature_shape is None:
            return  # input shape unknown (conv model without --feature-dim)
        self.predict_rows(
            np.zeros((1,) + self.feature_shape, dtype=np.float32))

    def predict_rows(self, x: np.ndarray) -> np.ndarray:
        """Rows in, logits out — the micro-batcher's execution surface (and
        the one padded-apply implementation ``predict`` wraps)."""
        x = np.asarray(x, dtype=np.float32)
        n = x.shape[0]
        if n > self.max_batch:
            raise ValueError(f"batch {n} exceeds max_batch {self.max_batch}")
        if self.feature_shape is None:
            self.feature_shape = tuple(x.shape[1:])
        pad = self.max_batch - n
        if pad:
            x = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)])
        logits = self._apply(self.variables, self._jnp.asarray(x))
        return np.asarray(logits)[:n]

    def predict(self, request: dict) -> dict:
        x = np.asarray(request["inputs"], dtype=np.float32)
        return {"outputs": self.predict_rows(x).tolist()}

    def predict_stream(self, request: dict):
        """One chunk per input row — the batched compute runs once, rows
        stream out as they are sliced (LLM predictors yield tokens here)."""
        out = self.predict(request)["outputs"]
        for i, row in enumerate(out):
            yield {"index": i, "outputs": row}


class FedMLInferenceRunner:
    """HTTP runner (``fedml_inference_runner.py``): POST /predict, GET /ready.

    With a ``batcher`` (ISSUE 11), plain JSON predicts route through the
    continuous micro-batcher: coalesced execution, bounded admission (queue
    overflow answers 503 + ``Retry-After``), and the response carries the
    model ``version`` that served it; ``GET /stats`` exposes the batcher +
    hot-swap accounting.  Streaming/file requests keep the direct path.
    """

    def __init__(self, predictor: FedMLPredictor, host: str = "127.0.0.1", port: int = 2345,
                 batcher=None, stats_fn=None, result_timeout_s: float = 30.0):
        self.predictor = predictor
        self.host = host
        self.port = port
        self.batcher = batcher
        self.stats_fn = stats_fn
        self.result_timeout_s = float(result_timeout_s)
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def _make_handler(self):
        predictor = self.predictor
        batcher = self.batcher
        stats_fn = self.stats_fn
        result_timeout_s = self.result_timeout_s

        class Handler(BaseHTTPRequestHandler):
            # chunked transfer is an HTTP/1.1 feature; the default HTTP/1.0
            # status line would make spec-compliant clients deliver the raw
            # chunk framing as body content
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _json(self, code: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/ready":
                    if predictor.ready():
                        self._json(200, {"status": "ready"})
                    else:
                        self._json(503, {"status": "not ready"})
                elif self.path == "/stats" and stats_fn is not None:
                    self._json(200, stats_fn())
                else:
                    self._json(404, {"error": "not found"})

            def do_POST(self):
                if self.path != "/predict":
                    self._json(404, {"error": "not found"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    request = json.loads(self.rfile.read(length).decode())
                    accept = self.headers.get("Accept", "application/json")
                    # a JSON reply satisfies the request if ANY member of the
                    # (possibly composite, parameterized) Accept list is JSON
                    # or a wildcard — 'application/json, text/plain, */*' and
                    # 'application/json; charset=utf-8' are JSON requests
                    wants = [m.split(";")[0].strip().lower()
                             for m in accept.split(",") if m.strip()]
                    json_ok = not wants or any(
                        m in ("application/json", "application/*", "*/*", "application/x-ndjson")
                        for m in wants
                    )
                    if not json_ok:
                        # reference FileResponse path: binary artifact reply
                        self._file(predictor.predict_file(request, accept), wants[0])
                        return
                    if request.get("stream", False):
                        self._stream(predictor.predict_stream(request))
                        return
                    if batcher is not None:
                        self._batched(request)
                        return
                    result = predictor.predict(request)
                    self._json(200, result)
                except Exception as e:  # surface the error to the caller
                    self._json(400, {"error": f"{type(e).__name__}: {e}"})

            def _batched(self, request: dict) -> None:
                """Continuous-batching predict: admission-bounded, answered
                with the serving model version; a full queue is explicit
                backpressure (503 + Retry-After), never silent queueing."""
                from .batcher import QueueOverflow

                try:
                    fut = batcher.submit(np.asarray(request["inputs"],
                                                    dtype=np.float32))
                except QueueOverflow as e:
                    body = json.dumps({"error": "overloaded",
                                       "retry_after_s": round(e.retry_after_s, 3)}).encode()
                    self.send_response(503)
                    self.send_header("Content-Type", "application/json")
                    # RFC 7231 delta-seconds (integer); the JSON body carries
                    # the precise estimate for richer clients
                    self.send_header("Retry-After",
                                     str(max(1, int(e.retry_after_s + 0.999))))
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                out = fut.wait(timeout=result_timeout_s)
                result = {"outputs": np.asarray(out).tolist()}
                if fut.version is not None:
                    result["version"] = int(fut.version)
                self._json(200, result)

            def _file(self, path: str, content_type: str) -> None:
                import os as _os
                import shutil as _shutil

                size = _os.path.getsize(path)  # pre-header failure -> clean 400
                with open(path, "rb") as f:
                    self.send_response(200)
                    self.send_header("Content-Type", content_type)
                    self.send_header("Content-Length", str(size))
                    self.end_headers()
                    try:
                        # stream, don't slurp: artifacts can be model files
                        _shutil.copyfileobj(f, self.wfile)
                    except Exception:
                        # headers are gone; a 400 here would corrupt the
                        # response — drop the connection (same as _stream)
                        self.close_connection = True

            def _stream(self, chunks) -> None:
                """Chunked transfer of newline-delimited JSON — the stdlib
                equivalent of the reference's StreamingResponse
                (``fedml_inference_runner.py:28``).  The first chunk is
                materialized BEFORE the headers go out so an immediately-
                failing predictor still produces a clean 400 (mid-stream
                failures can only truncate the chunked body — inherent to
                streaming)."""
                # dedicated empty-stream sentinel: a predictor may legally
                # yield a literal None (json 'null' is a valid NDJSON line)
                _empty = object()
                it = iter(chunks)
                try:
                    first = next(it)
                except StopIteration:
                    first = _empty
                    it = iter(())
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def put(chunk) -> None:
                    line = (json.dumps(chunk) + "\n").encode()
                    self.wfile.write(f"{len(line):x}\r\n".encode() + line + b"\r\n")
                    self.wfile.flush()

                try:
                    if first is not _empty:
                        put(first)
                    for chunk in it:
                        put(chunk)
                except Exception:
                    # headers are gone: a 400 written here would inject an
                    # HTTP status line into the chunked body (clients would
                    # read it as data or silent truncation).  Drop the
                    # connection WITHOUT the terminal 0-chunk so the client
                    # sees an aborted — not cleanly finished — stream.
                    self.close_connection = True
                    return
                self.wfile.write(b"0\r\n\r\n")

        return Handler

    def run(self, block: bool = True) -> int:
        """Start serving; returns the bound port (0 port -> ephemeral)."""
        self._server = ThreadingHTTPServer((self.host, self.port), self._make_handler())
        self.port = self._server.server_address[1]
        if block:
            self._server.serve_forever()
        else:
            self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
            self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._server:
            self._server.shutdown()
            self._server.server_close()
