"""Model serving: predictor interface + HTTP inference runner.

Parity with ``serving/fedml_predictor.py:4`` (user subclasses
``FedMLPredictor`` with ``predict``/``ready``) and
``serving/fedml_inference_runner.py:8`` (``FedMLInferenceRunner`` wraps it in
``POST /predict`` + ``GET /ready``).  The reference uses FastAPI; this build
serves the same routes from the stdlib ThreadingHTTPServer (FastAPI is not in
the image), so the client-side contract — JSON in, JSON out, 200/503 ready
semantics — is identical.

TPU notes: ``JaxPredictor`` jits the model apply once and pads request
batches to a fixed size so serving never retraces per request shape.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

import numpy as np


class FedMLPredictor:
    """Reference API shape (``fedml_predictor.py``)."""

    def predict(self, request: dict) -> Any:
        raise NotImplementedError

    def ready(self) -> bool:
        return True


class JaxPredictor(FedMLPredictor):
    """Serve a flax model: request {"inputs": [[...], ...]} -> {"outputs": ...}.

    Pads every batch to ``max_batch`` so one compiled program serves all
    request sizes (no per-shape retrace).
    """

    def __init__(self, model, variables, max_batch: int = 32):
        import jax
        import jax.numpy as jnp

        self.model = model
        self.variables = variables
        self.max_batch = max_batch
        self._apply = jax.jit(lambda v, x: model.apply(v, x, train=False))
        self._jnp = jnp

    def predict(self, request: dict) -> dict:
        x = np.asarray(request["inputs"], dtype=np.float32)
        n = x.shape[0]
        if n > self.max_batch:
            raise ValueError(f"batch {n} exceeds max_batch {self.max_batch}")
        pad = self.max_batch - n
        if pad:
            x = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)])
        logits = self._apply(self.variables, self._jnp.asarray(x))
        return {"outputs": np.asarray(logits)[:n].tolist()}


class FedMLInferenceRunner:
    """HTTP runner (``fedml_inference_runner.py``): POST /predict, GET /ready."""

    def __init__(self, predictor: FedMLPredictor, host: str = "127.0.0.1", port: int = 2345):
        self.predictor = predictor
        self.host = host
        self.port = port
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def _make_handler(self):
        predictor = self.predictor

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json(self, code: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/ready":
                    if predictor.ready():
                        self._json(200, {"status": "ready"})
                    else:
                        self._json(503, {"status": "not ready"})
                else:
                    self._json(404, {"error": "not found"})

            def do_POST(self):
                if self.path != "/predict":
                    self._json(404, {"error": "not found"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    request = json.loads(self.rfile.read(length).decode())
                    result = predictor.predict(request)
                    self._json(200, result)
                except Exception as e:  # surface the error to the caller
                    self._json(400, {"error": f"{type(e).__name__}: {e}"})

        return Handler

    def run(self, block: bool = True) -> int:
        """Start serving; returns the bound port (0 port -> ephemeral)."""
        self._server = ThreadingHTTPServer((self.host, self.port), self._make_handler())
        self.port = self._server.server_address[1]
        if block:
            self._server.serve_forever()
        else:
            self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
            self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._server:
            self._server.shutdown()
            self._server.server_close()
