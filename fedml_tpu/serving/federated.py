"""Federated serving managers — train-then-serve endpoints.

Parity with ``serving/fedml_server.py:4`` / ``serving/fedml_client.py``
(FedMLModelServingServer/Client): in the reference these are thin wrappers
that reuse the cross-silo server/client initializers under an endpoint
identity (end_point_name, model_name, model_version).  Same here — plus the
piece the reference leaves to its SaaS backend: when the federated run
completes, the final global model is registered as a ModelCard and (when a
deploy scheduler is given) deployed as a live endpoint, closing the
train->serve loop locally.
"""

from __future__ import annotations

from typing import Optional

from ..core.flags import cfg_extra
from ..cross_silo import build_client, build_server
from .deploy import ModelCard, ModelDeployScheduler, save_params_card


class FedMLModelServingServer:
    def __init__(self, cfg, end_point_name: str, model_name: str, model_version: str = "v1",
                 dataset=None, model=None, scheduler: Optional[ModelDeployScheduler] = None,
                 backend: Optional[str] = None):
        self.cfg = cfg
        self.end_point_name = end_point_name
        self.model_name = model_name
        self.model_version = model_version
        self.scheduler = scheduler
        self.dataset = dataset
        self.model = model
        if cfg.federated_optimizer not in ("FedAvg", "FedAvg_seq", "FedOpt", "FedProx"):
            # reference raises bare Exception for non-FedAvg; name the limit
            raise ValueError(
                f"federated serving supports FedAvg-family optimizers, got {cfg.federated_optimizer!r}"
            )
        self.server = build_server(cfg, dataset, model, backend=backend)

    def run(self, timeout: float = 600.0, artifact_dir: str = "/tmp/fedml_tpu_serving",
            replicas: int = 1):
        """Run the federated job; on completion register + deploy the model."""
        history = self.server.run_until_done(timeout=timeout)
        card = None
        if self.scheduler is not None:
            path = f"{artifact_dir}/{self.model_name}-{self.model_version}.wire"
            save_params_card(self.server.aggregator.global_vars, path)
            # extra.model_publish_dir rides into the card (ISSUE 11): the
            # deployed replicas watch the training server's manifest and
            # hot-swap versions live instead of serving a frozen artifact
            card = ModelCard(
                name=self.model_name, version=self.model_version,
                model=self.cfg.model, classes=self.dataset.class_num, params_path=path,
                publish_dir=cfg_extra(self.cfg, "model_publish_dir") or None,
            )
            self.scheduler.cards.register(card)
            self.scheduler.deploy(self.end_point_name, self.model_name,
                                  self.model_version, replicas=replicas)
        return history, card


class FedMLModelServingClient:
    def __init__(self, cfg, end_point_name: str, model_name: str, model_version: str = "v1",
                 dataset=None, model=None, rank: int = 1, backend: Optional[str] = None):
        self.end_point_name = end_point_name
        self.model_name = model_name
        self.model_version = model_version
        self.client = build_client(cfg, dataset, model, rank=rank, backend=backend)

    def run_in_thread(self):
        return self.client.run_in_thread()

    def finish(self):
        self.client.finish()
