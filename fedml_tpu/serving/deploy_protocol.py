"""Master/worker deploy protocol — deployment orchestrated over the comm plane.

Parity with the reference model scheduler's split
(``computing/scheduler/model_scheduler/master_protocol_manager.py`` /
``worker_protocol_manager.py``: the master receives a deployment request,
fans replica assignments out to worker edges, workers run the replicas via
the device deployment layer and report readiness; the master aggregates the
endpoint table and routes inference).  TPU build translation:

- :class:`DeployWorkerManager` — one per worker host; owns a local
  :class:`~fedml_tpu.serving.deploy.ModelDeployScheduler` (process replicas
  by default, any :class:`ReplicaRuntime` injectable) and answers
  DEPLOY/SCALE/UNDEPLOY commands, reporting ready replica ports.
- :class:`DeployMasterManager` — collects worker capacity reports, splits
  requested replicas across workers (capacity-weighted round-robin),
  aggregates readiness, and routes ``predict`` round-robin over every ready
  (worker, port) pair with failover.

Any comm backend carries the protocol (INPROC in tests; gRPC/TCP/MQTT for
real fleets).  Model weights travel by card reference (``params_path`` on a
shared filesystem / object store key), matching the reference's S3-by-
reference deployment packages.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request
from typing import Optional

from ..comm.comm_manager import FedMLCommManager
from ..comm.message import Message
from .deploy import ModelCard, ModelDeployScheduler

log = logging.getLogger("fedml_tpu.serving.deploy_protocol")

MSG_TYPE_W2M_WORKER_ONLINE = 60
MSG_TYPE_M2W_DEPLOY = 61
MSG_TYPE_W2M_REPLICA_STATUS = 62
MSG_TYPE_M2W_SCALE = 63
MSG_TYPE_M2W_UNDEPLOY = 64
MSG_TYPE_M2W_FINISH = 65

ARG_ENDPOINT = "endpoint"
ARG_CARD = "card_json"
ARG_REPLICAS = "replicas"
ARG_PORTS = "ready_ports"
ARG_HOST = "host"
ARG_CAPACITY = "capacity"


class DeployWorkerManager(FedMLCommManager):
    """Worker edge: local deploy scheduler behind the comm protocol
    (reference ``worker_protocol_manager.py`` + ``device_model_deployment``)."""

    def __init__(self, cfg, rank: int, workdir: str, backend: Optional[str] = None,
                 capacity: int = 4, host: str = "127.0.0.1", runtime=None,
                 report_interval_s: float = 0.3):
        super().__init__(cfg, rank=rank, size=0, backend=backend)
        self.sched = ModelDeployScheduler(
            f"{workdir}/worker{rank}.sqlite", reconcile_interval_s=0.5,
            runtime=runtime,
        )
        self.capacity = capacity
        self.host = host
        self.report_interval_s = report_interval_s
        self._stop = threading.Event()
        self._reporter: Optional[threading.Thread] = None

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(MSG_TYPE_M2W_DEPLOY, self.handle_deploy)
        self.register_message_receive_handler(MSG_TYPE_M2W_SCALE, self.handle_scale)
        self.register_message_receive_handler(MSG_TYPE_M2W_UNDEPLOY, self.handle_undeploy)
        self.register_message_receive_handler(MSG_TYPE_M2W_FINISH, self.handle_finish)

    def start(self) -> None:
        """Announce capacity (reference edges report on connect)."""
        msg = Message(MSG_TYPE_W2M_WORKER_ONLINE, self.rank, 0)
        msg.add_params(ARG_CAPACITY, self.capacity)
        msg.add_params(ARG_HOST, self.host)
        self.send_message(msg)
        self.sched.run_in_thread()
        self._reporter = threading.Thread(target=self._report_loop, daemon=True)
        self._reporter.start()

    # -- command handlers -----------------------------------------------------
    def handle_deploy(self, msg: Message) -> None:
        name = msg.get(ARG_ENDPOINT)
        card = ModelCard(**json.loads(msg.get(ARG_CARD)))
        replicas = int(msg.get(ARG_REPLICAS))
        try:
            if name in self.sched.endpoints:
                # a redelivered/duplicate DEPLOY must not overwrite the live
                # Endpoint record (the old replica processes would leak) —
                # but a duplicate carrying a DIFFERENT card means the master
                # wants a different model under this name; serving the old
                # one silently would be wrong, so say so loudly
                live = self.sched.endpoints[name].card
                if card != live:
                    log.warning(
                        "worker %d: duplicate DEPLOY for live endpoint %s "
                        "carries a different card (%s:%s vs live %s:%s) — "
                        "keeping the live model; undeploy first to replace",
                        self.rank, name, card.name, card.version,
                        live.name, live.version,
                    )
                self.sched.scale(name, replicas)
            else:
                self.sched.cards.register(card)
                self.sched.deploy(name, card.name, card.version, replicas=replicas)
        except Exception:
            log.exception("worker %d: deploy %s failed", self.rank, name)
        self._report(name)

    def handle_scale(self, msg: Message) -> None:
        name = msg.get(ARG_ENDPOINT)
        n = int(msg.get(ARG_REPLICAS))
        if n <= 0:
            # scaled off this worker entirely: drop the endpoint record,
            # not just its replicas (a zero-replica husk would linger)
            self.sched.undeploy(name)
        elif name in self.sched.endpoints:
            self.sched.scale(name, n)
        else:
            # scaled ONTO a worker that never hosted this endpoint: the
            # SCALE message carries the card so this is a fresh deploy
            card = ModelCard(**json.loads(msg.get(ARG_CARD)))
            try:
                self.sched.cards.register(card)
                self.sched.deploy(name, card.name, card.version, replicas=n)
            except Exception:
                log.exception("worker %d: scale-deploy %s failed", self.rank, name)
        self._report(name)

    def handle_undeploy(self, msg: Message) -> None:
        self.sched.undeploy(msg.get(ARG_ENDPOINT))
        self._report(msg.get(ARG_ENDPOINT))

    def handle_finish(self, msg: Message) -> None:
        self.stop()
        self.finish()

    # -- readiness reporting --------------------------------------------------
    def _report(self, endpoint: str) -> None:
        ep = self.sched.endpoints.get(endpoint)
        ports = ep.ready_ports() if ep is not None else []
        out = Message(MSG_TYPE_W2M_REPLICA_STATUS, self.rank, 0)
        out.add_params(ARG_ENDPOINT, endpoint)
        out.add_params(ARG_PORTS, [int(p) for p in ports])
        out.add_params(ARG_HOST, self.host)
        try:
            self.send_message(out)
        except Exception:
            log.debug("worker %d: status report undeliverable", self.rank)

    def _report_loop(self) -> None:
        """Readiness changes asynchronously (replica boot, crash-restart);
        report every endpoint periodically so the master's routing table
        converges without polling RPCs (reference workers push status)."""
        while not self._stop.wait(self.report_interval_s):
            for name in list(self.sched.endpoints):
                self._report(name)

    def stop(self) -> None:
        self._stop.set()
        self.sched.stop()


class DeployMasterManager(FedMLCommManager):
    """Master: placement + endpoint aggregation + inference routing
    (reference ``master_protocol_manager.py`` + the gateway role)."""

    def __init__(self, cfg, backend: Optional[str] = None):
        super().__init__(cfg, rank=0, size=0, backend=backend)
        self.workers: dict[int, dict] = {}           # rank -> {capacity, host}
        # endpoint -> worker rank -> {"ports": [...], "host": str}
        self.endpoints: dict[str, dict[int, dict]] = {}
        self.placements: dict[str, dict[int, int]] = {}
        # cards by endpoint: scale-up may land on a worker that never saw the
        # original DEPLOY, so SCALE messages re-ship the card
        self.cards: dict[str, ModelCard] = {}
        self._lock = threading.Lock()
        self._place_rr = 0   # placement rotation (under _lock)
        self._predict_rr = 0  # routing rotation (racy by design; benign)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(MSG_TYPE_W2M_WORKER_ONLINE, self.handle_worker_online)
        self.register_message_receive_handler(MSG_TYPE_W2M_REPLICA_STATUS, self.handle_replica_status)

    def handle_worker_online(self, msg: Message) -> None:
        with self._lock:
            self.workers[msg.get_sender_id()] = {
                "capacity": int(msg.get(ARG_CAPACITY)),
                "host": msg.get(ARG_HOST),
            }

    def handle_replica_status(self, msg: Message) -> None:
        name = msg.get(ARG_ENDPOINT)
        with self._lock:
            # reports for endpoints the master no longer tracks (undeployed)
            # are dropped — a report snapshotted before the UNDEPLOY landed
            # must not resurrect a stale routing entry with dead ports
            if name not in self.placements:
                return
            self.endpoints.setdefault(name, {})[msg.get_sender_id()] = {
                "ports": list(msg.get(ARG_PORTS)),
                "host": msg.get(ARG_HOST),
            }

    # -- orchestration API ----------------------------------------------------
    def wait_workers(self, n: int, timeout: float = 30.0) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._lock:
                if len(self.workers) >= n:
                    return
            time.sleep(0.05)
        with self._lock:
            online = len(self.workers)
        raise TimeoutError(f"only {online}/{n} workers reported online")

    def _place_locked(self, replicas: int, endpoint: str) -> dict[int, int]:
        """Capacity-weighted round-robin split (reference splits a
        deployment's replicas across selected edges).  Caller holds _lock.
        Free capacity accounts for every OTHER endpoint's current placement;
        the winning placement is COMMITTED to ``self.placements[endpoint]``
        before the lock is released, so concurrent deploys cannot both see
        the same free slot and over-commit the cluster.  Raises WITHOUT
        mutating state when capacity is short."""
        workers = dict(self.workers)
        if not workers:
            raise RuntimeError("no workers online")
        free = {r: int(w["capacity"]) for r, w in workers.items()}
        for name, held in self.placements.items():
            if name == endpoint:
                continue  # an endpoint being re-placed frees its own slots
            for r, n in held.items():
                free[r] = free.get(r, 0) - n
        placement = {r: 0 for r in workers}
        order = sorted(workers)
        i = self._place_rr
        placed = 0
        while placed < replicas and any(f > 0 for f in free.values()):
            r = order[i % len(order)]
            i += 1
            if free[r] > 0:
                placement[r] += 1
                free[r] -= 1
                placed += 1
        if placed < replicas:
            # raise BEFORE committing the cursor or the placement: a failed
            # attempt must leave no state (a retry sees identical conditions)
            raise RuntimeError(
                f"cluster capacity exhausted: placed {placed}/{replicas} replicas"
            )
        self._place_rr = i
        placement = {r: n for r, n in placement.items() if n > 0}
        self.placements[endpoint] = placement
        return placement

    def deploy(self, endpoint: str, card: ModelCard, replicas: int = 1) -> dict[int, int]:
        # ONE critical section for guard + placement + card commit: racing
        # duplicate deploys must not both pass the guard, and a failed
        # placement must leave NO state behind (messages go out after the
        # lock — workers' replies re-enter handlers that take _lock)
        with self._lock:
            if endpoint in self.placements:
                # re-deploying over a live name would orphan replicas on
                # workers the new placement omits (they'd keep serving the
                # OLD card through the routing table)
                raise ValueError(
                    f"endpoint {endpoint!r} is already deployed; scale() it "
                    "or undeploy() first"
                )
            placement = self._place_locked(replicas, endpoint)
            self.cards[endpoint] = card
        for rank, n in placement.items():
            msg = Message(MSG_TYPE_M2W_DEPLOY, 0, rank)
            msg.add_params(ARG_ENDPOINT, endpoint)
            msg.add_params(ARG_CARD, json.dumps(card.__dict__))
            msg.add_params(ARG_REPLICAS, n)
            self.send_message(msg)
        return placement

    def scale(self, endpoint: str, replicas: int) -> dict[int, int]:
        with self._lock:
            card = self.cards.get(endpoint)
            if card is None:
                # also covers scale-after-undeploy racing: once undeploy
                # popped the card, a late scale must refuse instead of
                # resurrecting a placement with no card behind it
                raise KeyError(f"endpoint {endpoint!r} was never deployed")
            old = dict(self.placements.get(endpoint, {}))
            placement = self._place_locked(replicas, endpoint)
        for rank in set(old) | set(placement):
            n = placement.get(rank, 0)
            msg = Message(MSG_TYPE_M2W_SCALE, 0, rank)
            msg.add_params(ARG_ENDPOINT, endpoint)
            msg.add_params(ARG_REPLICAS, n)
            # the card rides along: a scale-up may land on a worker that
            # never saw the original DEPLOY
            msg.add_params(ARG_CARD, json.dumps(card.__dict__))
            self.send_message(msg)
        return placement

    def undeploy(self, endpoint: str) -> None:
        # broadcast to EVERY known worker, not just the current placement:
        # re-placements (scale) may have left endpoint records on workers no
        # longer in the table, and a worker without the endpoint no-ops
        with self._lock:
            self.placements.pop(endpoint, None)
            self.cards.pop(endpoint, None)
            ranks = list(self.workers)
            self.endpoints.pop(endpoint, None)
        for rank in ranks:
            msg = Message(MSG_TYPE_M2W_UNDEPLOY, 0, rank)
            msg.add_params(ARG_ENDPOINT, endpoint)
            self.send_message(msg)

    def shutdown_workers(self) -> None:
        with self._lock:
            ranks = list(self.workers)
        for rank in ranks:
            self.send_message(Message(MSG_TYPE_M2W_FINISH, 0, rank))

    # -- routing (the gateway role over worker-hosted replicas) ---------------
    def ready_targets(self, endpoint: str) -> list[tuple[str, int]]:
        with self._lock:
            reports = dict(self.endpoints.get(endpoint, {}))
        return [(rep["host"], p) for _rank, rep in sorted(reports.items())
                for p in rep["ports"]]

    def wait_ready(self, endpoint: str, replicas: int, timeout: float = 60.0) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if len(self.ready_targets(endpoint)) >= replicas:
                return True
            time.sleep(0.1)
        return False

    def predict(self, endpoint: str, request: dict, timeout: float = 30.0) -> dict:
        targets = self.ready_targets(endpoint)
        if not targets:
            raise RuntimeError(f"endpoint {endpoint!r} has no ready replicas")
        body = json.dumps(request).encode()
        self._predict_rr += 1
        last_err: Optional[Exception] = None
        for i in range(len(targets)):
            host, port = targets[(self._predict_rr + i) % len(targets)]
            req = urllib.request.Request(
                f"http://{host}:{port}/predict", data=body,
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    return json.loads(r.read())
            except Exception as e:  # failover across workers AND replicas
                last_err = e
        raise RuntimeError(f"all replicas of {endpoint!r} failed: {last_err}")
