"""Model-deploy scheduler: endpoint lifecycle, replica control, autoscaling.

Capability parity with the reference's largest vertical,
``computing/scheduler/model_scheduler/`` (12.7k LoC):

- model cards            <- ``device_model_cards.py`` (register/list models)
- endpoint + replica DB  <- ``device_model_db.py`` (sqlite state)
- deployment             <- ``device_model_deployment.py:start_deployment``
- replica controller     <- ``device_replica_controller.py`` (desired vs
                            actual diff, rollout)
- health monitor         <- ``device_model_monitor.py`` + the readiness probe
                            ``is_client_inference_container_ready`` (:539)
- autoscaler             <- ``autoscaler/autoscaler.py`` (EWM + concurrency
                            policies, scale bounds, scale-down delay)
- inference gateway      <- ``device_model_inference.py`` (route requests to
                            ready replicas)

TPU-world divergences, by design: replicas are plain processes serving the
jitted predictor (no docker/triton — the runtime is jax itself); state is
sqlite (no redis); the gateway is in-process HTTP.  The reconcile loop is the
same desired-state pattern: every sweep compares the endpoint's desired
replica count against live+healthy processes, starts what's missing, stops
what's extra, and restarts what died — which is exactly the kill-and-recover
test in tests/test_deploy.py.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import math
import os
import socket
import sqlite3
import subprocess
import sys
import threading
import time
import urllib.request
from pathlib import Path
from typing import Optional

log = logging.getLogger("fedml_tpu.serving.deploy")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# model cards
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class ModelCard:
    """Reference ``device_model_cards.py``: a deployable (name, version,
    artifact) triple.  The artifact is a pytree-wire params file + the
    model-hub model name that interprets it.

    ``publish_dir`` (ISSUE 11): a training server's continuous-publication
    directory — replicas deployed from this card poll its manifest and
    hot-swap new versions live.  ``feature_dim`` names the input feature
    shape (comma-separated) for pre-serve warmup of conv models."""

    name: str
    version: str
    model: str          # model_hub name, e.g. "lr", "resnet20"
    classes: int
    params_path: str
    publish_dir: Optional[str] = None
    feature_dim: Optional[str] = None


class ModelCardRepo:
    def __init__(self):
        self._cards: dict[tuple[str, str], ModelCard] = {}

    def register(self, card: ModelCard) -> None:
        self._cards[(card.name, card.version)] = card

    def get(self, name: str, version: Optional[str] = None) -> ModelCard:
        if version is not None:
            return self._cards[(name, version)]
        versions = sorted(v for (n, v) in self._cards if n == name)
        if not versions:
            raise KeyError(f"no model card {name!r}")
        return self._cards[(name, versions[-1])]

    def list(self) -> list[ModelCard]:
        return list(self._cards.values())


def save_params_card(variables, path: str) -> str:
    """Serialize a model's variables to the pytree wire format (the same
    bytes the C++ client reads — one artifact format everywhere)."""
    import jax

    from ..comm import wire

    Path(path).parent.mkdir(parents=True, exist_ok=True)
    with open(path, "wb") as f:
        f.write(wire.encode_pytree(jax.device_get(variables)))
    return path


# ---------------------------------------------------------------------------
# endpoint/replica state (sqlite, reference device_model_db.py)
# ---------------------------------------------------------------------------
class EndpointDB:
    def __init__(self, path: str):
        self.path = path
        with self._conn() as c:
            c.execute(
                "CREATE TABLE IF NOT EXISTS endpoints ("
                "name TEXT PRIMARY KEY, model TEXT, version TEXT, "
                "desired INTEGER, status TEXT, created REAL)"
            )
            c.execute(
                "CREATE TABLE IF NOT EXISTS replicas ("
                "endpoint TEXT, idx INTEGER, pid INTEGER, port INTEGER, "
                "status TEXT, restarts INTEGER DEFAULT 0, "
                "PRIMARY KEY (endpoint, idx))"
            )
            # per-endpoint request telemetry — the signals the autoscaler
            # acts on, persisted every reconcile sweep so operators can see
            # WHY a scale decision happened (reference stores request stats
            # in its device DB for the autoscaler the same way)
            c.execute(
                "CREATE TABLE IF NOT EXISTS request_stats ("
                "endpoint TEXT PRIMARY KEY, requests INTEGER, qps REAL, "
                "latency_ms_ewm REAL, inflight INTEGER, updated REAL)"
            )

    def _conn(self):
        return sqlite3.connect(self.path)

    def upsert_endpoint(self, name: str, model: str, version: str, desired: int, status: str) -> None:
        with self._conn() as c:
            c.execute(
                "INSERT INTO endpoints VALUES (?,?,?,?,?,?) ON CONFLICT(name) DO UPDATE "
                "SET desired=excluded.desired, status=excluded.status",
                (name, model, version, desired, status, time.time()),
            )

    def set_desired(self, name: str, desired: int) -> None:
        with self._conn() as c:
            c.execute("UPDATE endpoints SET desired=? WHERE name=?", (desired, name))

    def endpoint(self, name: str) -> Optional[dict]:
        with self._conn() as c:
            row = c.execute(
                "SELECT name, model, version, desired, status FROM endpoints WHERE name=?", (name,)
            ).fetchone()
        if row is None:
            return None
        return dict(zip(("name", "model", "version", "desired", "status"), row))

    def upsert_replica(self, endpoint: str, idx: int, pid: int, port: int, status: str) -> None:
        with self._conn() as c:
            c.execute(
                "INSERT INTO replicas (endpoint, idx, pid, port, status) VALUES (?,?,?,?,?) "
                "ON CONFLICT(endpoint, idx) DO UPDATE SET pid=excluded.pid, "
                "port=excluded.port, status=excluded.status, restarts=restarts+1",
                (endpoint, idx, pid, port, status),
            )

    def replicas(self, endpoint: str) -> list[dict]:
        with self._conn() as c:
            rows = c.execute(
                "SELECT idx, pid, port, status, restarts FROM replicas WHERE endpoint=? ORDER BY idx",
                (endpoint,),
            ).fetchall()
        return [dict(zip(("idx", "pid", "port", "status", "restarts"), r)) for r in rows]

    def delete_replica(self, endpoint: str, idx: int) -> None:
        with self._conn() as c:
            c.execute("DELETE FROM replicas WHERE endpoint=? AND idx=?", (endpoint, idx))

    def upsert_stats(self, endpoint: str, requests: int, qps: float,
                     latency_ms_ewm: Optional[float], inflight: int) -> None:
        with self._conn() as c:
            c.execute(
                "INSERT INTO request_stats VALUES (?,?,?,?,?,?) "
                "ON CONFLICT(endpoint) DO UPDATE SET requests=excluded.requests, "
                "qps=excluded.qps, latency_ms_ewm=excluded.latency_ms_ewm, "
                "inflight=excluded.inflight, updated=excluded.updated",
                (endpoint, requests, qps, latency_ms_ewm, inflight, time.time()),
            )

    def stats(self, endpoint: str) -> Optional[dict]:
        with self._conn() as c:
            row = c.execute(
                "SELECT endpoint, requests, qps, latency_ms_ewm, inflight, updated "
                "FROM request_stats WHERE endpoint=?", (endpoint,)
            ).fetchone()
        if row is None:
            return None
        return dict(zip(("endpoint", "requests", "qps", "latency_ms_ewm", "inflight", "updated"), row))


# ---------------------------------------------------------------------------
# autoscaler (reference autoscaler/autoscaler.py)
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class AutoscalePolicy:
    min_replicas: int = 1
    max_replicas: int = 4
    target_qps_per_replica: float = 50.0
    ewm_alpha: float = 0.5              # reference ewm latest-weight
    scaledown_delay_s: float = 30.0     # reference enforce_scaling_down_delay_interval
    policy: str = "ewm"                 # "ewm" | "concurrency"
    target_concurrency_per_replica: float = 4.0


class Autoscaler:
    """EWM/concurrency scaling decisions with bounds + scale-down delay —
    the reference's ``scale_operation_ewm`` / ``scale_operation_query_concurrency``
    reduced to their decision logic (no redis; metrics come from the
    gateway's counters)."""

    def __init__(self, policy: AutoscalePolicy):
        self.policy = policy
        self._ewm: Optional[float] = None
        self._scaledown_since: Optional[float] = None

    def desired(self, current: int, qps: float, concurrency: float, now: Optional[float] = None) -> int:
        p = self.policy
        now = time.time() if now is None else now
        if p.policy == "concurrency":
            raw = concurrency / p.target_concurrency_per_replica
        else:
            self._ewm = qps if self._ewm is None else p.ewm_alpha * qps + (1 - p.ewm_alpha) * self._ewm
            raw = self._ewm / p.target_qps_per_replica
        want = max(p.min_replicas, min(p.max_replicas, math.ceil(raw) if raw > 0 else p.min_replicas))
        if want < current:
            # reference: scaling down must persist for the delay interval
            if self._scaledown_since is None:
                self._scaledown_since = now
                return current
            if now - self._scaledown_since < p.scaledown_delay_s:
                return current
            self._scaledown_since = None
            return want
        self._scaledown_since = None
        return want


# ---------------------------------------------------------------------------
# replica handler + controller (reference device_replica_{handler,controller}.py)
# ---------------------------------------------------------------------------
def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def probe_ready(port: int, timeout: float = 1.0) -> bool:
    """Reference ``is_client_inference_container_ready``: GET /ready."""
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/ready", timeout=timeout) as r:
            return r.status == 200 and json.loads(r.read()).get("status") == "ready"
    except Exception:
        return False


class ReplicaRuntime:
    """The runtime seam: HOW a replica executes (reference
    ``device_model_deployment.py``'s role — there a docker/triton container,
    here a subprocess by default).  The scheduler/controller above this
    interface is runtime-agnostic: a container implementation plugs in by
    injecting another ReplicaRuntime into :class:`ModelDeployScheduler`.

    Handles are opaque to the scheduler; it only ever passes them back into
    this interface."""

    def start(self, card: ModelCard) -> tuple[object, int]:
        """Launch one replica of ``card``; return (handle, http_port)."""
        raise NotImplementedError

    def stop(self, handle) -> None:
        raise NotImplementedError

    def poll(self, handle) -> Optional[int]:
        """None while running; the exit code once the replica died."""
        raise NotImplementedError

    def replica_id(self, handle) -> int:
        """Stable numeric id for the DB row (pid / container number)."""
        raise NotImplementedError


class ProcessReplicaRuntime(ReplicaRuntime):
    """Default runtime: one ``serving.worker`` subprocess per replica
    (reference device_replica_handler's spawn/stop)."""

    def start(self, card: ModelCard) -> tuple[subprocess.Popen, int]:
        port = _free_port()
        cmd = [sys.executable, "-m", "fedml_tpu.serving.worker",
               "--model", card.model, "--classes", str(card.classes),
               "--params", card.params_path, "--port", str(port)]
        if card.publish_dir:
            cmd += ["--publish-dir", card.publish_dir]
        if card.feature_dim:
            cmd += ["--feature-dim", str(card.feature_dim)]
        proc = subprocess.Popen(
            cmd,
            cwd=_REPO_ROOT,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            env={**os.environ, "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu")},
        )
        return proc, port

    def stop(self, handle: Optional[subprocess.Popen]) -> None:
        if handle is not None and handle.poll() is None:
            handle.terminate()
            try:
                handle.wait(timeout=5)
            except subprocess.TimeoutExpired:
                handle.kill()

    def poll(self, handle: subprocess.Popen) -> Optional[int]:
        return handle.poll()

    def replica_id(self, handle: subprocess.Popen) -> int:
        return handle.pid


class Endpoint:
    """Desired-state record + live process table for one deployed model."""

    def __init__(self, name: str, card: ModelCard, desired: int,
                 autoscale: Optional[AutoscalePolicy],
                 runtime: Optional[ReplicaRuntime] = None):
        self.name = name
        self.card = card
        self.desired = desired
        self.runtime = runtime or ProcessReplicaRuntime()
        self.autoscaler = Autoscaler(autoscale) if autoscale else None
        # opaque runtime handles by replica index (Popen for the default
        # process runtime; container records for an injected runtime)
        self.procs: dict[int, object] = {}
        self.ports: dict[int, int] = {}
        self.request_count = 0
        self.inflight = 0
        self.latency_ms_ewm: Optional[float] = None
        self._last_rate_t = time.time()
        self._last_rate_n = 0
        # guards procs/ports: the reconcile thread mutates them while predict/
        # ready_ports iterate from request threads
        self.lock = threading.Lock()
        # set by undeploy: a reconcile sweep that snapshotted this endpoint
        # before the pop must not resurrect its replicas
        self.closed = False

    def qps(self) -> float:
        now = time.time()
        dt = max(now - self._last_rate_t, 1e-6)
        rate = (self.request_count - self._last_rate_n) / dt
        self._last_rate_t = now
        self._last_rate_n = self.request_count
        return rate

    def record_latency(self, seconds: float, alpha: float = 0.3) -> None:
        ms = seconds * 1000.0
        with self.lock:
            self.latency_ms_ewm = (
                ms if self.latency_ms_ewm is None
                else alpha * ms + (1 - alpha) * self.latency_ms_ewm
            )

    def ready_ports(self) -> list[int]:
        # snapshot under the lock, probe outside it (probes do HTTP)
        with self.lock:
            live = [
                p for idx, p in sorted(self.ports.items())
                if self.procs.get(idx) is not None
                and self.runtime.poll(self.procs[idx]) is None
            ]
        return [p for p in live if probe_ready(p)]


class ModelDeployScheduler:
    """The deploy vertical's front door (reference model_device_server +
    device_server_runner reduced to a library): deploy -> reconcile loop ->
    scale/undeploy."""

    def __init__(self, db_path: str, reconcile_interval_s: float = 1.0,
                 runtime: Optional[ReplicaRuntime] = None):
        self.db = EndpointDB(db_path)
        self.cards = ModelCardRepo()
        self.endpoints: dict[str, Endpoint] = {}
        self.runtime = runtime  # None -> each Endpoint gets the process default
        self.reconcile_interval_s = reconcile_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.RLock()
        # deploy()/scale() call reconcile_once inline while the background
        # loop runs the same sweep; serializing sweeps prevents double-starting
        # the same replica index (the loser's process would leak)
        self._reconcile_lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------
    def deploy(self, endpoint_name: str, model_name: str, version: Optional[str] = None,
               replicas: int = 1, autoscale: Optional[AutoscalePolicy] = None) -> Endpoint:
        card = self.cards.get(model_name, version)
        ep = Endpoint(endpoint_name, card, replicas, autoscale, runtime=self.runtime)
        with self._lock:
            self.endpoints[endpoint_name] = ep
        self.db.upsert_endpoint(endpoint_name, card.model, card.version, replicas, "DEPLOYING")
        self.reconcile_once()
        return ep

    def scale(self, endpoint_name: str, replicas: int) -> None:
        with self._lock:
            self.endpoints[endpoint_name].desired = replicas
        self.db.set_desired(endpoint_name, replicas)
        self.reconcile_once()

    def undeploy(self, endpoint_name: str) -> None:
        with self._lock:
            ep = self.endpoints.pop(endpoint_name, None)
        if ep is None:
            return
        with ep.lock:
            ep.closed = True
        # serialize with the sweep: a reconcile that snapshotted this endpoint
        # before the pop must fully drain before we stop processes and write
        # the terminal DB state, or it could resurrect replicas / overwrite
        # the UNDEPLOYED record
        with self._reconcile_lock:
            with ep.lock:
                stopping = list(ep.procs.items())
                ep.procs.clear()
                ep.ports.clear()
            for idx, proc in stopping:
                ep.runtime.stop(proc)
                self.db.delete_replica(endpoint_name, idx)
            self.db.upsert_endpoint(endpoint_name, ep.card.model, ep.card.version, 0, "UNDEPLOYED")

    # -- the reconcile loop (replica controller + monitor) -------------------
    def reconcile_once(self) -> None:
        with self._reconcile_lock:
            self._reconcile_impl()

    def _install_replica(self, ep: Endpoint, idx: int, status: str) -> bool:
        """Start one replica and register it; if the endpoint was undeployed
        while the process was starting, stop it again instead of leaking it.
        Returns False when the endpoint is gone (caller abandons the sweep)."""
        proc, port = ep.runtime.start(ep.card)
        with ep.lock:
            if ep.closed:
                abandoned = True
            else:
                abandoned = False
                ep.procs[idx] = proc
                ep.ports[idx] = port
        if abandoned:
            ep.runtime.stop(proc)
            return False
        self.db.upsert_replica(ep.name, idx, ep.runtime.replica_id(proc), port, status)
        return True

    def _reconcile_impl(self) -> None:
        with self._lock:
            eps = list(self.endpoints.values())
        for ep in eps:
            self._reconcile_endpoint(ep)

    def _reconcile_endpoint(self, ep: Endpoint) -> None:
        if ep.closed:
            return
        # autoscaling first: it updates desired before the diff; the same
        # measured signals are persisted so operators can audit the decision
        qps = ep.qps()
        self.db.upsert_stats(ep.name, ep.request_count, qps, ep.latency_ms_ewm, ep.inflight)
        if ep.autoscaler is not None:
            ep.desired = ep.autoscaler.desired(
                current=max(len(ep.procs), 1), qps=qps, concurrency=ep.inflight,
            )
        # restart dead replicas (the monitor role)
        with ep.lock:
            dead = [
                (idx, ep.procs[idx], rc) for idx, proc in ep.procs.items()
                if (rc := ep.runtime.poll(proc)) is not None and idx < ep.desired
            ]
        for idx, handle, rc in dead:
            log.warning("endpoint %s replica %d died (rc=%s); restarting",
                        ep.name, idx, rc)
            # release the dead handle through the seam BEFORE replacing it:
            # for the process runtime this is a no-op on an exited Popen, but
            # a container runtime must get the chance to remove the exited
            # container (ports/disk/records) or they accumulate per restart
            ep.runtime.stop(handle)
            if not self._install_replica(ep, idx, "RESTARTING"):
                return  # endpoint undeployed mid-sweep: abandon it entirely
        # start missing replicas
        with ep.lock:
            missing = [idx for idx in range(ep.desired) if idx not in ep.procs]
        for idx in missing:
            if not self._install_replica(ep, idx, "STARTING"):
                return
        # stop extras (scale-down)
        with ep.lock:
            extras = [
                (idx, ep.procs.pop(idx), ep.ports.pop(idx, None))
                for idx in [i for i in ep.procs if i >= ep.desired]
            ]
        for idx, proc, _port in extras:
            ep.runtime.stop(proc)
            self.db.delete_replica(ep.name, idx)
        if ep.closed:  # best-effort probe-skip; undeploy's terminal DB write
            return      # is serialized after this sweep via _reconcile_lock
        ready = ep.ready_ports()
        status = "READY" if len(ready) >= min(ep.desired, 1) else "DEPLOYING"
        self.db.upsert_endpoint(ep.name, ep.card.model, ep.card.version, ep.desired, status)

    def run_in_thread(self) -> threading.Thread:
        def loop():
            while not self._stop.wait(self.reconcile_interval_s):
                try:
                    self.reconcile_once()
                except Exception:  # reconcile must survive everything
                    log.exception("reconcile sweep failed")
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self._thread

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        for name in list(self.endpoints):
            self.undeploy(name)

    # -- readiness + inference routing (gateway, device_model_inference) -----
    def wait_ready(self, endpoint_name: str, replicas: int = 1, timeout: float = 60.0) -> bool:
        t0 = time.time()
        while time.time() - t0 < timeout:
            ep = self.endpoints.get(endpoint_name)
            if ep is not None and len(ep.ready_ports()) >= replicas:
                return True
            time.sleep(0.2)
        return False

    def _gateway_attempts(self, endpoint_name: str, request: dict):
        """Shared gateway preamble: counts the request and yields round-robin
        (endpoint, urllib Request) attempts over the ready replicas."""
        ep = self.endpoints[endpoint_name]
        ports = ep.ready_ports()
        if not ports:
            raise RuntimeError(f"endpoint {endpoint_name!r} has no ready replicas")
        ep.request_count += 1
        start = ep.request_count
        body = json.dumps(request).encode()
        for i in range(len(ports)):
            port = ports[(start + i) % len(ports)]
            yield ep, urllib.request.Request(
                f"http://127.0.0.1:{port}/predict",
                data=body,
                headers={"Content-Type": "application/json"},
            )

    def predict(self, endpoint_name: str, request: dict, timeout: float = 30.0) -> dict:
        """Round-robin over ready replicas with failover (the gateway).
        Records request latency into the endpoint's EWM (the autoscaler's
        persisted signal)."""
        last_err: Optional[Exception] = None
        for ep, req in self._gateway_attempts(endpoint_name, request):
            t0 = time.time()
            try:
                ep.inflight += 1
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    out = json.loads(r.read())
                ep.record_latency(time.time() - t0)
                return out
            except Exception as e:  # failover to the next replica
                last_err = e
            finally:
                ep.inflight -= 1
        raise RuntimeError(f"all replicas of {endpoint_name!r} failed: {last_err}")

    def predict_stream(self, endpoint_name: str, request: dict, timeout: float = 30.0):
        """Streaming gateway: forwards ``stream=True`` to a ready replica and
        yields the newline-delimited JSON chunks as they arrive.  Failover
        applies only before the first chunk (a partially-consumed stream
        cannot be replayed)."""
        last_err: Optional[Exception] = None
        body = dict(request)
        body["stream"] = True
        for ep, req in self._gateway_attempts(endpoint_name, body):
            t0 = time.time()
            try:
                resp = urllib.request.urlopen(req, timeout=timeout)
            except Exception as e:
                last_err = e
                continue

            # Count the stream as inflight from the moment the response is
            # open — a caller that never iterates must not be invisible to the
            # autoscaler, and abandoning the stream must release the socket at
            # close(), not at GC.  A plain generator can't guarantee that: its
            # finally never runs if iteration never starts.
            ep.inflight += 1
            return _StreamHandle(ep, resp, t0)
        raise RuntimeError(f"all replicas of {endpoint_name!r} failed: {last_err}")


class _StreamHandle:
    """Iterator over a replica's NDJSON stream whose accounting (inflight,
    latency EWM, socket close) runs exactly once — on exhaustion, error,
    explicit close(), or GC — even if the caller never iterates."""

    def __init__(self, ep, resp, t0):
        self._ep, self._resp, self._t0 = ep, resp, t0
        self._finished = False

    def __iter__(self):
        return self

    def __next__(self):
        if self._finished:
            raise StopIteration
        try:
            for line in self._resp:
                line = line.strip()
                if line:
                    return json.loads(line)
            self._finish()
            raise StopIteration
        except StopIteration:
            raise
        except Exception:
            self._finish()
            raise

    def _finish(self, record: bool = True) -> None:
        if self._finished:
            return
        self._finished = True
        self._ep.inflight -= 1
        if record:
            self._ep.record_latency(time.time() - self._t0)
        try:
            self._resp.close()
        except Exception:
            pass

    def close(self) -> None:
        self._finish()

    def __del__(self):
        # GC path: skip record_latency — it takes ep.lock, and a finalizer
        # triggered by cyclic GC may run on a thread that already holds it
        # (deadlock).  Socket close + lock-free inflight decrement only.
        self._finish(record=False)
