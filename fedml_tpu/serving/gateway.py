"""Tenant-routed serving gateway — one front door for a shared worker fleet
(ISSUE 19).

The PR-11 serving stack is per-tenant: each :class:`ServingWorker` binds one
``model_publish_dir``, hot-swaps that tenant's versions, and answers on its
own port.  A fleet running N training jobs publishes N manifests, so callers
had to know every worker's address.  This module closes the loop:

- :class:`ServingGateway` listens on ONE port and routes each request by its
  ``tenant`` id to the worker bound to that tenant's publish dir (ModelCards
  already carry ``publish_dir`` — :meth:`ServingGateway.add_tenant` accepts
  either a card or an explicit address);
- requests for the same tenant are **coalesced at the gateway**
  (``extra.gateway_max_batch`` rows / ``extra.gateway_flush_ms`` window)
  before one forwarded ``POST /predict`` hits the worker, whose own
  micro-batcher then sees fuller batches across replicas of callers;
- responses carry the ``version`` the worker served AND the tenant id, so
  every answer is attributable to exactly one tenant's manifest — the
  zero-bleed property the fleet bench hard-asserts;
- a full per-tenant queue answers 503 + ``Retry-After`` (the same explicit
  backpressure contract as the worker), and an unknown tenant answers 404 —
  never a silent misroute.

Workers keep serving their own ports untouched — a deployment without a
gateway is byte-identical to PR-11.
"""

from __future__ import annotations

import http.client
import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from ..core.flags import cfg_extra
from ..obs import registry as obsreg

log = logging.getLogger("fedml_tpu.serving.gateway")

__all__ = ["ServingGateway", "GatewayOverflow", "gateway_from_config"]

GATEWAY_REQUESTS = obsreg.REGISTRY.counter(
    "fedml_gateway_requests_total",
    "Requests at the tenant-routed gateway, by tenant and outcome "
    "(ok / unknown_tenant / overflow / error).",
    labels=("tenant", "outcome"),
)
GATEWAY_BATCHES = obsreg.REGISTRY.counter(
    "fedml_gateway_batches_total",
    "Coalesced batches the gateway forwarded to a tenant's worker.",
    labels=("tenant",),
)
GATEWAY_BATCH_FILL = obsreg.REGISTRY.histogram(
    "fedml_gateway_batch_fill",
    "Rows per forwarded gateway batch (fill against gateway_max_batch).",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128),
)
GATEWAY_REQUEST_TIME = obsreg.REGISTRY.histogram(
    "fedml_gateway_request_seconds",
    "Gateway request latency end to end: admission, coalescing window, "
    "worker round trip.",
    labels=("tenant",),
)
GATEWAY_TENANTS = obsreg.REGISTRY.gauge(
    "fedml_gateway_tenants",
    "Tenants currently routed by the serving gateway.",
)


class GatewayOverflow(RuntimeError):
    """A tenant's gateway queue is full — explicit backpressure."""

    def __init__(self, depth: int, retry_after_s: float):
        super().__init__(f"gateway queue full ({depth} pending)")
        self.depth = depth
        self.retry_after_s = float(retry_after_s)


class _GatewayRequest:
    """One caller's rows riding a coalesced forward."""

    __slots__ = ("rows", "event", "outputs", "version", "error")

    def __init__(self, rows: np.ndarray):
        self.rows = rows
        self.event = threading.Event()
        self.outputs = None
        self.version: Optional[int] = None
        self.error: Optional[str] = None


class _TenantLane:
    """Per-tenant coalescing queue + dispatcher: submit rows, the lane
    batches co-tenant requests for up to ``flush_ms`` / ``max_batch`` rows,
    forwards ONE ``POST /predict`` to the tenant's worker, and splits the
    outputs back per caller.

    Thread model (GL008-audited): ``_pending``/counters under ``_cond``
    (one lock for the whole lane); the dispatcher drains under it and
    forwards outside it; callers block on their request's event.
    """

    def __init__(self, tenant: str, address: tuple, *,
                 publish_dir: Optional[str] = None, max_batch: int = 8,
                 max_queue: int = 256, flush_ms: float = 2.0,
                 timeout_s: float = 30.0):
        self.tenant = str(tenant)
        self.address = (str(address[0]), int(address[1]))
        self.publish_dir = publish_dir
        self.max_batch = max(1, int(max_batch))
        self.max_queue = max(1, int(max_queue))
        self.flush_s = max(0.0, float(flush_ms)) / 1000.0
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: list[_GatewayRequest] = []
        self._stop = False
        self._forwarded = 0
        self._last_version: Optional[int] = None
        self._thread = threading.Thread(
            target=self._loop, name=f"gateway-{tenant}", daemon=True)
        self._thread.start()

    def submit(self, rows: np.ndarray) -> _GatewayRequest:
        req = _GatewayRequest(rows)
        with self._cond:
            depth = sum(r.rows.shape[0] for r in self._pending)
            if depth + rows.shape[0] > self.max_queue:
                raise GatewayOverflow(
                    depth, retry_after_s=max(self.flush_s, 0.05))
            self._pending.append(req)
            self._cond.notify()
        return req

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._stop:
                    self._cond.wait(timeout=0.2)
                if self._stop and not self._pending:
                    return
                # coalescing window: let co-tenant rows join this batch
                if self.flush_s > 0 and not self._stop:
                    deadline = time.monotonic() + self.flush_s
                    while (sum(r.rows.shape[0] for r in self._pending)
                           < self.max_batch):
                        left = deadline - time.monotonic()
                        if left <= 0:
                            break
                        self._cond.wait(timeout=left)
                batch: list[_GatewayRequest] = []
                rows = 0
                while self._pending and rows < self.max_batch:
                    batch.append(self._pending.pop(0))
                    rows += batch[-1].rows.shape[0]
            self._forward(batch)

    def _forward(self, batch: list[_GatewayRequest]) -> None:
        rows = np.concatenate([r.rows for r in batch])
        GATEWAY_BATCHES.inc(tenant=self.tenant)
        GATEWAY_BATCH_FILL.observe(float(rows.shape[0]))
        try:
            conn = http.client.HTTPConnection(*self.address,
                                              timeout=self.timeout_s)
            try:
                body = json.dumps({"inputs": rows.tolist()})
                conn.request("POST", "/predict", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                payload = json.loads(resp.read().decode())
                if resp.status != 200:
                    raise RuntimeError(
                        f"worker answered {resp.status}: "
                        f"{payload.get('error', payload)}")
            finally:
                conn.close()
            outputs = np.asarray(payload["outputs"])
            version = payload.get("version")
            off = 0
            with self._cond:
                self._forwarded += len(batch)
                if version is not None:
                    self._last_version = int(version)
            for req in batch:
                n = req.rows.shape[0]
                req.outputs = outputs[off:off + n]
                req.version = None if version is None else int(version)
                off += n
                req.event.set()
        except Exception as e:  # noqa: BLE001 — every caller gets the reason
            log.warning("gateway forward to tenant %s failed: %s",
                        self.tenant, e)
            for req in batch:
                req.error = f"{type(e).__name__}: {e}"
                req.event.set()

    def stats(self) -> dict:
        with self._cond:
            return {
                "address": f"{self.address[0]}:{self.address[1]}",
                "publish_dir": self.publish_dir,
                "pending": sum(r.rows.shape[0] for r in self._pending),
                "forwarded": self._forwarded,
                "last_version": self._last_version,
            }

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=5.0)


class ServingGateway:
    """One HTTP front door routing ``{"tenant": ..., "inputs": ...}`` to the
    tenant's worker, with per-tenant gateway-side coalescing."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 max_batch: int = 8, flush_ms: float = 2.0,
                 max_queue: int = 256, result_timeout_s: float = 30.0):
        self.host = host
        self.port = int(port)
        self.max_batch = int(max_batch)
        self.flush_ms = float(flush_ms)
        self.max_queue = int(max_queue)
        self.result_timeout_s = float(result_timeout_s)
        self._lanes: dict[str, _TenantLane] = {}
        self._lock = threading.Lock()
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- routing table --------------------------------------------------------
    def add_tenant(self, tenant: str, *, port: int,
                   host: str = "127.0.0.1",
                   publish_dir: Optional[str] = None,
                   card=None) -> None:
        """Route ``tenant`` to the worker at ``host:port``.  ``card`` (a
        serving ModelCard) supplies ``publish_dir`` when one isn't given —
        the manifest root every answered version is attributable to."""
        if card is not None and publish_dir is None:
            publish_dir = getattr(card, "publish_dir", None)
        with self._lock:
            old = self._lanes.pop(str(tenant), None)
            self._lanes[str(tenant)] = _TenantLane(
                tenant, (host, port), publish_dir=publish_dir,
                max_batch=self.max_batch, max_queue=self.max_queue,
                flush_ms=self.flush_ms, timeout_s=self.result_timeout_s)
            GATEWAY_TENANTS.set(len(self._lanes))
        if old is not None:
            old.stop()

    def remove_tenant(self, tenant: str) -> None:
        with self._lock:
            lane = self._lanes.pop(str(tenant), None)
            GATEWAY_TENANTS.set(len(self._lanes))
        if lane is not None:
            lane.stop()

    def lane_of(self, tenant: str) -> Optional[_TenantLane]:
        with self._lock:
            return self._lanes.get(str(tenant))

    # -- request path ---------------------------------------------------------
    def handle(self, request: dict) -> tuple[int, dict]:
        """Route one decoded request; returns (http status, response body).
        Factored off the HTTP handler so in-process callers (tests, the
        dryrun stage) exercise the exact serving path."""
        tenant = str(request.get("tenant", ""))
        lane = self.lane_of(tenant)
        if lane is None:
            GATEWAY_REQUESTS.inc(tenant=tenant or "?", outcome="unknown_tenant")
            return 404, {"error": f"unknown tenant {tenant!r}"}
        t0 = time.monotonic()
        try:
            rows = np.asarray(request["inputs"], dtype=np.float32)
            req = lane.submit(rows)
        except GatewayOverflow as e:
            GATEWAY_REQUESTS.inc(tenant=tenant, outcome="overflow")
            return 503, {"error": "overloaded",
                         "retry_after_s": round(e.retry_after_s, 3)}
        except Exception as e:  # noqa: BLE001 — malformed inputs answer 400
            GATEWAY_REQUESTS.inc(tenant=tenant, outcome="error")
            return 400, {"error": f"{type(e).__name__}: {e}"}
        if not req.event.wait(timeout=self.result_timeout_s):
            GATEWAY_REQUESTS.inc(tenant=tenant, outcome="error")
            return 504, {"error": "worker timed out"}
        if req.error is not None:
            GATEWAY_REQUESTS.inc(tenant=tenant, outcome="error")
            return 502, {"error": req.error}
        GATEWAY_REQUESTS.inc(tenant=tenant, outcome="ok")
        GATEWAY_REQUEST_TIME.observe(time.monotonic() - t0, tenant=tenant)
        out = {"tenant": tenant, "outputs": np.asarray(req.outputs).tolist()}
        if req.version is not None:
            out["version"] = int(req.version)
        return 200, out

    def stats(self) -> dict:
        with self._lock:
            lanes = dict(self._lanes)
        return {"tenants": {t: lane.stats() for t, lane in lanes.items()}}

    # -- HTTP front -----------------------------------------------------------
    def _make_handler(self):
        gw = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _json(self, code: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                if code == 503 and "retry_after_s" in obj:
                    self.send_header(
                        "Retry-After",
                        str(max(1, int(obj["retry_after_s"] + 0.999))))
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/ready":
                    self._json(200, {"status": "ready",
                                     "tenants": len(gw._lanes)})
                elif self.path == "/stats":
                    self._json(200, gw.stats())
                else:
                    self._json(404, {"error": "not found"})

            def do_POST(self):
                if self.path != "/predict":
                    self._json(404, {"error": "not found"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    request = json.loads(self.rfile.read(length).decode())
                except Exception as e:  # noqa: BLE001
                    self._json(400, {"error": f"{type(e).__name__}: {e}"})
                    return
                code, body = gw.handle(request)
                self._json(code, body)

        return Handler

    def start(self, block: bool = False) -> int:
        """Bind and serve; returns the bound port."""
        self._server = ThreadingHTTPServer((self.host, self.port),
                                           self._make_handler())
        self.port = self._server.server_address[1]
        if block:
            self._server.serve_forever()
        else:
            self._thread = threading.Thread(
                target=self._server.serve_forever,
                name="fedml-gateway", daemon=True)
            self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        with self._lock:
            lanes = list(self._lanes.values())
            self._lanes.clear()
            GATEWAY_TENANTS.set(0)
        for lane in lanes:
            lane.stop()


def gateway_from_config(cfg, **overrides) -> ServingGateway:
    """A gateway shaped by the ``extra.gateway_*`` flags (port / batch cap /
    flush window); keyword overrides win, matching the worker builders."""
    kw = {
        "port": int(cfg_extra(cfg, "gateway_port") or 0),
        "max_batch": int(cfg_extra(cfg, "gateway_max_batch")),
        "flush_ms": float(cfg_extra(cfg, "gateway_flush_ms")),
    }
    kw.update(overrides)
    return ServingGateway(**kw)
