"""Continuous model publication + hot-swap (ISSUE 11).

Closes the training→serving loop: the async server bumps ``server_version``
at every virtual-round finalize and the sync server at every round, but
nothing ever carried those versions to the inference fleet — redeploying a
model meant restarting workers with a new ``--params`` file.  This module
is the publication channel, built from the two disk patterns already proven
in this codebase:

- **Server side** (:class:`ModelPublisher`, behind the registered
  ``extra.model_publish_dir`` flag): at every version bump the server
  atomically writes ``params-v<version>.wire`` (pytree wire format — the
  same bytes the deploy artifacts and the C++ client read) via
  tmp+``os.replace``, then rewrites ``MANIFEST.json`` the same way.  The
  manifest is the commit record (journal/AOT-store pattern): readers see
  the previous or the complete new version, never a torn one.  Old param
  files are pruned past ``extra.model_publish_keep``.
- **Worker side** (:class:`ManifestWatcher` + :class:`HotSwapController`):
  workers poll the manifest and hot-swap the parameter tree BETWEEN
  micro-batches with zero dropped in-flight requests — the new tree is
  decoded and warmed (one padded execution through the already-compiled
  apply) while the old tree keeps serving; only then does the route flip.
  With ``canary_fraction`` set, the new version first serves that fraction
  of micro-batches while a multiplicative health score (the
  ``obs.health.ClientHealthLedger`` scoring shape: independent penalty
  factors for errors/non-finite outputs and latency regression vs the
  stable EWMA, score in [0,1]) accumulates; a score under
  ``regress_threshold`` after ``canary_min_batches`` rolls the version
  back — it is remembered as rejected and never re-offered.

Default path bit-identical: ``publisher_from_config`` returns ``None`` when
``extra.model_publish_dir`` is unset — no publisher object, no disk writes,
server rounds byte-for-byte what they were before the flag existed.

Thread model (GL008-audited): the publisher is called only from the
server's locked round boundary (single caller thread); the controller's
state mutates under its own ``_lock`` — ``route``/``observe_batch`` run on
the batcher's dispatcher thread, ``offer`` on the watcher thread, and
``stats`` on request threads.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import re
import tempfile
import threading
import time
from typing import Any, Callable, Optional

from ..core.flags import cfg_extra
from ..obs import registry as obsreg

log = logging.getLogger("fedml_tpu.serving.publisher")

__all__ = [
    "ModelPublisher", "publisher_from_config", "ManifestWatcher",
    "HotSwapController", "MANIFEST_NAME",
]

MANIFEST_NAME = "MANIFEST.json"
_PARAMS_RE = re.compile(r"^params-v(\d{8})\.wire$")

PUBLISHES = obsreg.REGISTRY.counter(
    "fedml_serving_publishes_total",
    "Model versions published to the serving manifest by the training server.",
)
PUBLISHED_VERSION = obsreg.REGISTRY.gauge(
    "fedml_serving_published_version",
    "Latest model version committed to the serving manifest.",
)
SERVED_VERSION = obsreg.REGISTRY.gauge(
    "fedml_serving_served_version",
    "Model version the stable (non-canary) serving route currently uses.",
)
SWAPS = obsreg.REGISTRY.counter(
    "fedml_serving_hot_swaps_total",
    "Model versions promoted to the stable serving route (hot swaps).",
)
ROLLBACKS = obsreg.REGISTRY.counter(
    "fedml_serving_rollbacks_total",
    "Canary versions rolled back on a health regression.",
)
CANARY_BATCHES = obsreg.REGISTRY.counter(
    "fedml_serving_canary_batches_total",
    "Micro-batches routed to a canary version, by outcome.",
    labels=("outcome",),
)


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------
class ModelPublisher:
    """Atomic version-stamped publication into one directory (see module
    docstring).  ``publish`` never raises into the caller's round — a disk
    failure logs and skips the version (the next bump retries)."""

    def __init__(self, root: str, keep: int = 5):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.keep = max(1, int(keep))
        self.published = 0
        self.last_version: Optional[int] = None

    def _params_name(self, version: int) -> str:
        return f"params-v{int(version):08d}.wire"

    def _atomic_write(self, name: str, blob: bytes) -> str:
        path = os.path.join(self.root, name)
        fd, tmp = tempfile.mkstemp(dir=self.root, prefix=".tmp_", suffix=".pub")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)  # readers see old or complete new
        except OSError:
            with contextlib.suppress(OSError):
                os.remove(tmp)
            raise
        return path

    def publish(self, version: int, variables: Any, meta: Optional[dict] = None) -> Optional[str]:
        """Write ``variables`` as version ``version`` and commit the manifest.
        Returns the params path, or None when the write failed (logged)."""
        from ..comm import wire

        try:
            blob = wire.encode_pytree(variables)
            name = self._params_name(version)
            self._atomic_write(name, blob)
            manifest = {
                "version": int(version),
                "path": name,
                "nbytes": len(blob),
                "created_unix": round(time.time(), 3),
                **(meta or {}),
            }
            self._atomic_write(
                MANIFEST_NAME,
                json.dumps(manifest, sort_keys=True, indent=1).encode())
        except Exception:
            log.warning("model publish of version %s failed; the next version "
                        "bump retries", version, exc_info=True)
            return None
        self.published += 1
        self.last_version = int(version)
        PUBLISHES.inc()
        PUBLISHED_VERSION.set(float(version))
        self._prune(keep_name=name)
        return os.path.join(self.root, name)

    def _prune(self, keep_name: str) -> None:
        """Retain the newest ``keep`` param files; the manifest-referenced
        file is never pruned regardless of age."""
        try:
            entries = sorted(
                f for f in os.listdir(self.root) if _PARAMS_RE.match(f))
        except OSError:
            return
        for stale in entries[:-self.keep]:
            if stale == keep_name:
                continue
            with contextlib.suppress(OSError):
                os.remove(os.path.join(self.root, stale))


def publisher_from_config(cfg) -> Optional[ModelPublisher]:
    """The one gate: ``extra.model_publish_dir`` unset/falsy → ``None``
    (no publisher object, no writes — the pre-flag server byte-identical)."""
    if cfg is None or not cfg_extra(cfg, "model_publish_dir"):
        return None
    root = str(cfg_extra(cfg, "model_publish_dir"))
    keep = int(cfg_extra(cfg, "model_publish_keep"))
    try:
        return ModelPublisher(root, keep=keep)
    except OSError as e:
        log.warning("model publish dir %s unusable (%s) — publication "
                    "disabled for this run", root, e)
        return None


# ---------------------------------------------------------------------------
# worker side
# ---------------------------------------------------------------------------
class ManifestWatcher:
    """Poll-side reader of a publisher directory: ``poll()`` returns
    ``(version, params_path, manifest)`` when the manifest names a version
    newer than the last one returned, else ``None``.  Corrupt or missing
    manifests read as "nothing new" (the atomic replace means the previous
    complete manifest was the last good state)."""

    def __init__(self, root: str, last_version: int = -1):
        self.root = os.path.abspath(root)
        self.last_version = int(last_version)

    def read_manifest(self) -> Optional[dict]:
        try:
            with open(os.path.join(self.root, MANIFEST_NAME)) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return None
        if not isinstance(manifest, dict) or "version" not in manifest:
            return None
        return manifest

    def poll(self) -> Optional[tuple[int, str, dict]]:
        manifest = self.read_manifest()
        if manifest is None:
            return None
        version = int(manifest["version"])
        if version <= self.last_version:
            return None
        path = os.path.join(self.root, str(manifest.get("path", "")))
        if not os.path.exists(path):
            return None  # manifest ahead of a pruned/failed params write
        self.last_version = version
        return version, path, manifest

    def wait_for_version(self, min_version: int = 0, timeout_s: float = 30.0,
                         poll_s: float = 0.05) -> Optional[tuple[int, str, dict]]:
        """Block until the manifest reaches ``min_version`` (worker
        bootstrap: serve the first published model without a --params file)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            got = self.poll()
            if got is not None and got[0] >= min_version:
                return got
            time.sleep(poll_s)
        return None


class HotSwapController:
    """Stable/canary routing + promotion/rollback for one serving worker.

    The batcher calls :meth:`route` per micro-batch and reports the outcome
    through :meth:`observe_batch`; the watcher thread calls :meth:`offer`
    with a WARMED predictor for a new version.  ``canary_fraction <= 0``
    means direct promotion (the zero-downtime swap: old tree serves until
    the new one warmed, then the route flips between micro-batches).
    """

    def __init__(self, predictor, version: int = 0, *,
                 canary_fraction: float = 0.0, canary_min_batches: int = 8,
                 regress_threshold: float = 0.5, latency_factor: float = 3.0,
                 error_weight: float = 4.0, eval_batch=None):
        self._lock = threading.Lock()
        self._stable = (predictor, int(version))
        self._canary: Optional[tuple[Any, int]] = None
        self.canary_fraction = float(canary_fraction)
        self.canary_min_batches = max(1, int(canary_min_batches))
        self.regress_threshold = float(regress_threshold)
        self.latency_factor = float(latency_factor)
        self.error_weight = float(error_weight)
        #: optional labeled eval batch ``(x, y)``: each offered canary is
        #: scored on REAL held-out accuracy (off the serving path, on the
        #: watcher thread) and an accuracy regression vs the stable version
        #: multiplies into the health score — a numerically healthy but
        #: WRONG model now rolls back too
        self.eval_batch = None
        if eval_batch is not None:
            import numpy as _np

            ex, ey = eval_batch
            self.eval_batch = (_np.asarray(ex, dtype=_np.float32),
                               _np.asarray(ey))
        self.swaps = 0
        self.rollbacks = 0
        self.rejected: set[int] = set()
        self._batch_idx = 0
        self._stable_lat_ewma: Optional[float] = None
        self._canary_errors = 0.0
        self._canary_lat_ewma: Optional[float] = None
        self._canary_batches = 0
        self._stable_eval_acc = self._eval_accuracy(predictor)
        self._canary_eval_acc: Optional[float] = None
        SERVED_VERSION.set(float(version))

    def _eval_accuracy(self, predictor) -> Optional[float]:
        """Accuracy of ``predictor`` on the labeled eval batch (None without
        one, or when the predictor cannot score it — never raises into the
        swap path)."""
        if self.eval_batch is None or predictor is None:
            return None
        import numpy as _np

        ex, ey = self.eval_batch
        try:
            logits = _np.asarray(predictor.predict_rows(ex))
            return float(_np.mean(_np.argmax(logits, axis=-1)
                                  == _np.asarray(ey).reshape(-1)))
        except Exception:
            log.warning("canary eval-batch scoring failed; accuracy factor "
                        "skipped for this version", exc_info=True)
            return None

    # -- routing (batcher dispatcher thread) ----------------------------------
    def route(self) -> tuple[Any, int, bool]:
        with self._lock:
            self._batch_idx += 1
            if self._canary is not None and self.canary_fraction > 0:
                period = max(1, round(1.0 / self.canary_fraction))
                if self._batch_idx % period == 0:
                    pred, ver = self._canary
                    return pred, ver, True
            pred, ver = self._stable
            return pred, ver, False

    def stable(self) -> tuple[Any, int, bool]:
        with self._lock:
            pred, ver = self._stable
            return pred, ver, False

    def observe_batch(self, version: int, ok: bool, execute_s: float,
                      is_canary: bool, fallback: bool = False) -> None:
        """One micro-batch outcome.  ``fallback`` marks a canary batch that
        regressed (exception or non-finite outputs) and was re-run on the
        stable route — the hardest possible evidence against the canary."""
        with self._lock:
            if not is_canary:
                self._stable_lat_ewma = (
                    execute_s if self._stable_lat_ewma is None
                    else 0.3 * execute_s + 0.7 * self._stable_lat_ewma)
                return
            if self._canary is None or self._canary[1] != version:
                return  # stale report from a canary already decided
            self._canary_batches += 1
            if fallback or not ok:
                self._canary_errors += 1.0
                CANARY_BATCHES.inc(outcome="error")
            else:
                self._canary_lat_ewma = (
                    execute_s if self._canary_lat_ewma is None
                    else 0.3 * execute_s + 0.7 * self._canary_lat_ewma)
                CANARY_BATCHES.inc(outcome="ok")
            if self._canary_batches >= self.canary_min_batches:
                if self._health_score_locked() >= self.regress_threshold:
                    self._promote_locked()
                else:
                    self._rollback_locked()

    def _health_score_locked(self) -> float:  # graftlint: disable=GL004(caller holds _lock: observe_batch/offer call these inside their critical sections)
        """Multiplicative health in [0,1] (the health-ledger scoring shape):
        an error factor ``1/(1 + w*errors)`` times a latency factor that
        only kicks in past ``latency_factor`` x the stable EWMA."""
        score = 1.0 / (1.0 + self.error_weight * self._canary_errors)
        if self._stable_lat_ewma and self._canary_lat_ewma:
            limit = self.latency_factor * self._stable_lat_ewma
            if self._canary_lat_ewma > limit:
                score *= limit / self._canary_lat_ewma
        # real eval-set factor: a canary whose held-out accuracy fell below
        # the stable version's is penalized proportionally (same
        # multiplicative shape as the other factors — an improvement never
        # boosts past 1.0)
        if (self._canary_eval_acc is not None
                and self._stable_eval_acc is not None
                and self._stable_eval_acc > 0
                and self._canary_eval_acc < self._stable_eval_acc):
            score *= self._canary_eval_acc / self._stable_eval_acc
        return score

    def _promote_locked(self) -> None:  # graftlint: disable=GL004(caller holds _lock: observe_batch/offer call these inside their critical sections)
        pred, ver = self._canary
        self._stable = (pred, ver)
        self._canary = None
        if self._canary_eval_acc is not None:
            self._stable_eval_acc = self._canary_eval_acc
        self._canary_eval_acc = None
        self.swaps += 1
        SWAPS.inc()
        SERVED_VERSION.set(float(ver))
        log.info("hot swap: version %d promoted to the stable route "
                 "(swap #%d)", ver, self.swaps)

    def _rollback_locked(self) -> None:  # graftlint: disable=GL004(caller holds _lock: observe_batch/offer call these inside their critical sections)
        _pred, ver = self._canary
        self._canary = None
        self.rejected.add(ver)
        self.rollbacks += 1
        ROLLBACKS.inc()
        log.warning("canary rollback: version %d health %.3f < %.3f after "
                    "%d batches (%.0f errors, eval acc %s vs stable %s) — "
                    "stable version %d keeps serving", ver,
                    self._health_score_locked(), self.regress_threshold,
                    self._canary_batches, self._canary_errors,
                    self._canary_eval_acc, self._stable_eval_acc,
                    self._stable[1])
        self._canary_eval_acc = None

    # -- publication intake (watcher thread) ----------------------------------
    def wants_version(self, version: int) -> bool:
        with self._lock:
            return (version > self._stable[1]
                    and version not in self.rejected
                    and (self._canary is None or version > self._canary[1]))

    def offer(self, version: int, predictor) -> None:
        """Install a WARMED predictor for ``version``: direct promotion when
        canary routing is off, else as the canary under a fresh score.  With
        an eval batch configured, the candidate is scored on it HERE (the
        watcher thread, off the serving path) so the accuracy factor is in
        place before the first canary batch reports."""
        eval_acc = self._eval_accuracy(predictor)
        with self._lock:
            if version <= self._stable[1] or version in self.rejected:
                return
            if self.canary_fraction <= 0:
                self._canary = (predictor, version)
                self._canary_eval_acc = eval_acc
                self._promote_locked()
                return
            self._canary = (predictor, version)
            self._canary_errors = 0.0
            self._canary_lat_ewma = None
            self._canary_batches = 0
            self._canary_eval_acc = eval_acc

    # -- introspection --------------------------------------------------------
    @property
    def version(self) -> int:
        with self._lock:
            return self._stable[1]

    def stats(self) -> dict:
        with self._lock:
            return {
                "served_version": self._stable[1],
                "canary_version": self._canary[1] if self._canary else None,
                "swaps": self.swaps,
                "rollbacks": self.rollbacks,
                "rejected_versions": sorted(self.rejected),
                "canary_fraction": self.canary_fraction,
                "stable_eval_acc": self._stable_eval_acc,
                "canary_eval_acc": self._canary_eval_acc,
            }


def watch_and_swap(watcher: ManifestWatcher, controller: HotSwapController,
                   load_predictor: Callable[[int, str, dict], Any],
                   stop: threading.Event, poll_s: float = 0.25) -> threading.Thread:
    """The worker's hot-swap loop on a daemon thread: poll the manifest,
    decode + warm the new tree via ``load_predictor`` (called OFF the
    serving path — the old tree serves throughout), then ``offer`` it.
    Load failures are logged and retried at the next poll."""

    def loop():
        while not stop.wait(poll_s):
            got = watcher.poll()
            if got is None:
                continue
            version, path, manifest = got
            if not controller.wants_version(version):
                continue
            try:
                predictor = load_predictor(version, path, manifest)
            except Exception:
                log.warning("could not load published version %d from %s; "
                            "retrying at the next poll", version, path,
                            exc_info=True)
                watcher.last_version = version - 1  # re-see it next poll
                continue
            controller.offer(version, predictor)

    t = threading.Thread(target=loop, name="fedml-serving-watcher", daemon=True)
    t.start()
    return t
