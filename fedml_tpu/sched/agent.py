"""Worker agent — consume run packages, execute, monitor, report.

Parity with the reference's slave/master agent runners
(``computing/scheduler/slave/client_runner.py:62`` — download package, rewrite
config, bootstrap, spawn the user job as a subprocess; status reporting to a
job DB; ``comm_utils/job_monitor.py:48`` — liveness sweeps).  This build's
compact agent keeps the exact pipeline over the local spool:

  queue/*.zip -> unzip to runs/<id>/ -> bootstrap -> spawn subprocess
  -> sqlite status DB (reference client_data_interface.py keeps sqlite too)
  -> JobMonitor sweep marks dead processes FAILED / reaps zombies.
"""

from __future__ import annotations

import json
import os
import shutil
import sqlite3
import subprocess
import sys
import threading
import time
import zipfile
from pathlib import Path
from typing import Optional


class JobDB:
    """sqlite job table (reference ``client_data_interface.py``)."""

    def __init__(self, path: str):
        self.path = path
        with self._conn() as c:
            c.execute(
                "CREATE TABLE IF NOT EXISTS jobs ("
                "run_id TEXT PRIMARY KEY, status TEXT, pid INTEGER, "
                "returncode INTEGER, started REAL, finished REAL, log_path TEXT)"
            )

    def _conn(self):
        return sqlite3.connect(self.path)

    def upsert(self, run_id: str, **fields) -> None:
        with self._conn() as c:
            cur = c.execute("SELECT run_id FROM jobs WHERE run_id=?", (run_id,))
            if cur.fetchone() is None:
                c.execute("INSERT INTO jobs (run_id, status) VALUES (?, 'QUEUED')", (run_id,))
            sets = ", ".join(f"{k}=?" for k in fields)
            c.execute(f"UPDATE jobs SET {sets} WHERE run_id=?", (*fields.values(), run_id))

    def get(self, run_id: str) -> Optional[dict]:
        with self._conn() as c:
            c.row_factory = sqlite3.Row
            row = c.execute("SELECT * FROM jobs WHERE run_id=?", (run_id,)).fetchone()
            return dict(row) if row else None

    def all_jobs(self) -> list[dict]:
        with self._conn() as c:
            c.row_factory = sqlite3.Row
            return [dict(r) for r in c.execute("SELECT * FROM jobs")]


def parse_requirements(computing: Optional[dict]) -> tuple[int, str, float]:
    """The job ``computing`` contract, in ONE place (agent claim check and
    spool matcher must agree): (devices, device type, min memory GB)."""
    comp = computing or {}
    return (
        int(comp.get("minimum_num_gpus", 1)),
        str(comp.get("request_gpu_type", "") or ""),
        float(comp.get("minimum_memory_gb", 0) or 0),
    )


def satisfies(req: tuple[int, str, float], capacity: dict, free_devices: int) -> bool:
    """Can an agent with ``capacity`` and ``free_devices`` run ``req`` now?
    A ``mem_gb`` of 0/absent means unlimited (the CLI's documented
    contract) — an agent that declares no memory bound accepts any job."""
    need_dev, need_type, need_mem = req
    if need_dev > free_devices:
        return False
    if need_type and need_type != str(capacity.get("device_type", "")):
        return False
    mem_cap = float(capacity.get("mem_gb", 0) or 0) or float("inf")
    if need_mem > mem_cap:
        return False
    return True


class FedMLAgent:
    """One worker agent bound to a spool directory.

    ``capacity`` registers what this agent can run (reference: edges report
    their resources and ``scheduler_matcher.py:6`` matches requests against
    them): ``num_devices``, ``device_type``, ``mem_gb``.  The agent writes a
    heartbeat record into ``spool/agents/<id>.json`` every sweep and only
    claims packages whose ``computing`` requirements it satisfies with its
    currently-free devices — an oversized job stays queued for a bigger
    agent instead of being grabbed by whoever polls first."""

    def __init__(self, spool_dir: str, env: Optional[dict] = None,
                 agent_id: str = "", capacity: Optional[dict] = None):
        self.spool = Path(spool_dir)
        self.queue = self.spool / "queue"
        self.runs = self.spool / "runs"
        self.agents_dir = self.spool / "agents"
        self.queue.mkdir(parents=True, exist_ok=True)
        self.runs.mkdir(parents=True, exist_ok=True)
        self.agents_dir.mkdir(parents=True, exist_ok=True)
        self.db = JobDB(str(self.spool / "jobs.sqlite"))
        self.env = env
        self.agent_id = agent_id or f"agent_{os.getpid()}"
        self.capacity = dict(capacity or {"num_devices": 1})
        # the sweep thread (run_in_thread) and the caller (stop, fits,
        # process_package from tests/CLI) both touch the run ledger; every
        # access to _procs/_alloc/_manifest_cache holds _state_lock
        self._state_lock = threading.Lock()
        self._procs: dict[str, subprocess.Popen] = {}
        self._alloc: dict[str, int] = {}  # run_id -> devices held
        # parsed-manifest cache keyed by (name, size, mtime): unfitting
        # packages stay queued across many polls and must not be re-opened
        # and re-parsed twice a second forever
        self._manifest_cache: dict[tuple, dict] = {}
        self._running = threading.Event()
        self._register()

    # -- capacity registration / matching ------------------------------------
    def _register(self) -> None:
        with self._state_lock:
            running = sorted(self._alloc)
        record = {
            "id": self.agent_id,
            **self.capacity,
            "free_devices": self.free_devices(),
            "running": running,
            "heartbeat": time.time(),
        }
        tmp = self.agents_dir / f".{self.agent_id}.tmp"
        tmp.write_text(json.dumps(record))
        tmp.replace(self.agents_dir / f"{self.agent_id}.json")

    def free_devices(self) -> int:
        with self._state_lock:
            held = sum(self._alloc.values())
        return int(self.capacity.get("num_devices", 1)) - held

    def fits(self, manifest: dict) -> bool:
        """Does this agent currently satisfy the job's computing section?"""
        return satisfies(parse_requirements(manifest.get("computing")),
                         self.capacity, self.free_devices())

    # -- package pipeline (reference run_impl :480) --------------------------
    def process_package(self, pkg: Path, manifest: Optional[dict] = None) -> str:
        with zipfile.ZipFile(pkg) as z:
            if manifest is None:
                manifest = json.loads(z.read("__fedml_job__.json"))
            run_id = manifest["run_id"]
            run_dir = self.runs / run_id
            run_dir.mkdir(parents=True, exist_ok=True)
            z.extractall(run_dir)
        pkg.unlink()  # claimed
        log_path = str(run_dir / "job.log")
        self.db.upsert(run_id, status="PROVISIONING", log_path=log_path)
        logf = open(log_path, "ab")
        env = dict(os.environ)
        # the job runs with cwd=run_dir, so a package doing `import
        # fedml_tpu` must find THIS checkout even when the framework isn't
        # pip-installed: put the directory containing the fedml_tpu package
        # on the child's PYTHONPATH (an explicit self.env override wins)
        pkg_parent = str(Path(__file__).resolve().parents[2])
        existing = env.get("PYTHONPATH")
        env["PYTHONPATH"] = pkg_parent + (os.pathsep + existing if existing else "")
        if self.env:
            env.update(self.env)
        env["FEDML_RUN_ID"] = run_id
        # bootstrap (reference bootstrap :395)
        if manifest.get("bootstrap"):
            rc = subprocess.call(
                manifest["bootstrap"], shell=True, cwd=run_dir, stdout=logf, stderr=logf, env=env
            )
            if rc != 0:
                self.db.upsert(run_id, status="FAILED", returncode=rc, finished=time.time())
                logf.close()
                return run_id
        proc = subprocess.Popen(
            manifest["job"], shell=True, cwd=run_dir, stdout=logf, stderr=logf, env=env
        )
        with self._state_lock:
            self._procs[run_id] = proc
            self._alloc[run_id] = parse_requirements(manifest.get("computing"))[0]
        self.db.upsert(run_id, status="RUNNING", pid=proc.pid, started=time.time())
        return run_id

    def sweep_once(self) -> list[str]:
        """One scheduling pass: claim queued packages + reap finished jobs
        (the JobMonitor role, ``job_monitor.py:337``)."""
        claimed = []
        seen_keys = set()
        for pkg in sorted(self.queue.glob("*.zip")):
            try:
                st = pkg.stat()
                key = (pkg.name, st.st_size, st.st_mtime_ns)
                seen_keys.add(key)
                with self._state_lock:
                    manifest = self._manifest_cache.get(key)
                if manifest is None:
                    with zipfile.ZipFile(pkg) as z:
                        manifest = json.loads(z.read("__fedml_job__.json"))
                    with self._state_lock:
                        self._manifest_cache[key] = manifest
            except (FileNotFoundError, zipfile.BadZipFile, KeyError):
                continue  # claimed by another agent / still being written
            if not self.fits(manifest):
                continue  # stays queued for an agent that satisfies it
            try:
                claimed.append(self.process_package(pkg, manifest=manifest))
            except FileNotFoundError:
                continue  # another agent claimed it between check and claim
        with self._state_lock:
            procs = list(self._procs.items())
        for run_id, proc in procs:
            rc = proc.poll()
            if rc is not None:
                self.db.upsert(
                    run_id,
                    status="FINISHED" if rc == 0 else "FAILED",
                    returncode=rc, finished=time.time(),
                )
                with self._state_lock:
                    self._procs.pop(run_id, None)
                    self._alloc.pop(run_id, None)  # free the devices
        # drop cache entries for packages no longer in the queue
        with self._state_lock:
            self._manifest_cache = {
                k: v for k, v in self._manifest_cache.items() if k in seen_keys
            }
        self._register()  # heartbeat + free-capacity refresh
        return claimed

    def run_forever(self, poll_s: float = 0.5) -> None:
        self._running.set()
        while self._running.is_set():
            self.sweep_once()
            time.sleep(poll_s)

    def run_in_thread(self, poll_s: float = 0.5) -> threading.Thread:
        t = threading.Thread(target=self.run_forever, args=(poll_s,), daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        self._running.clear()
        with self._state_lock:
            procs = list(self._procs.items())
        for run_id, proc in procs:
            proc.terminate()
            self.db.upsert(run_id, status="UNDETERMINED")

    def wait_for(self, run_id: str, timeout: float = 120.0) -> dict:
        deadline = time.time() + timeout
        while time.time() < deadline:
            self.sweep_once()
            row = self.db.get(run_id)
            if row and row["status"] in ("FINISHED", "FAILED"):
                return row
            time.sleep(0.2)
        raise TimeoutError(f"job {run_id} did not finish in {timeout}s")

    def logs(self, run_id: str) -> str:
        row = self.db.get(run_id)
        if not row or not row.get("log_path"):
            return ""
        p = Path(row["log_path"])
        return p.read_text() if p.exists() else ""


def match_resources(jobs: list[dict], agents: list[dict]) -> dict[str, str]:
    """Scheduler matcher (reference ``scheduler_matcher.py:6``): assign each
    job to an agent satisfying its ``computing`` section — device count
    against free devices, requested device type exact-match, minimum memory —
    first-fit decreasing on device demand.  Unmatchable jobs are absent from
    the result (they stay queued)."""
    assignment: dict[str, str] = {}
    free = {a["id"]: int(a.get("free_devices", a.get("num_devices", 1))) for a in agents}
    info = {a["id"]: a for a in agents}
    reqs = {j["run_id"]: parse_requirements(j.get("computing")) for j in jobs}
    for job in sorted(jobs, key=lambda j: -reqs[j["run_id"]][0]):
        req = reqs[job["run_id"]]
        for aid, avail in sorted(free.items(), key=lambda kv: -kv[1]):
            if satisfies(req, info[aid], avail):
                assignment[job["run_id"]] = aid
                free[aid] -= req[0]
                break
    return assignment


def registered_agents(spool_dir: str, max_age_s: float = 60.0) -> list[dict]:
    """Read live agent capacity records from ``spool/agents/`` (stale
    heartbeats are dropped — a dead agent must not attract assignments)."""
    out = []
    agents_dir = Path(spool_dir) / "agents"
    now = time.time()
    for p in sorted(agents_dir.glob("*.json")):
        try:
            rec = json.loads(p.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        if now - float(rec.get("heartbeat", 0)) <= max_age_s:
            out.append(rec)
    return out
