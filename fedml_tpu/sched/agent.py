"""Worker agent — consume run packages, execute, monitor, report.

Parity with the reference's slave/master agent runners
(``computing/scheduler/slave/client_runner.py:62`` — download package, rewrite
config, bootstrap, spawn the user job as a subprocess; status reporting to a
job DB; ``comm_utils/job_monitor.py:48`` — liveness sweeps).  This build's
compact agent keeps the exact pipeline over the local spool:

  queue/*.zip -> unzip to runs/<id>/ -> bootstrap -> spawn subprocess
  -> sqlite status DB (reference client_data_interface.py keeps sqlite too)
  -> JobMonitor sweep marks dead processes FAILED / reaps zombies.
"""

from __future__ import annotations

import json
import os
import shutil
import sqlite3
import subprocess
import sys
import threading
import time
import zipfile
from pathlib import Path
from typing import Optional


class JobDB:
    """sqlite job table (reference ``client_data_interface.py``)."""

    def __init__(self, path: str):
        self.path = path
        with self._conn() as c:
            c.execute(
                "CREATE TABLE IF NOT EXISTS jobs ("
                "run_id TEXT PRIMARY KEY, status TEXT, pid INTEGER, "
                "returncode INTEGER, started REAL, finished REAL, log_path TEXT)"
            )

    def _conn(self):
        return sqlite3.connect(self.path)

    def upsert(self, run_id: str, **fields) -> None:
        with self._conn() as c:
            cur = c.execute("SELECT run_id FROM jobs WHERE run_id=?", (run_id,))
            if cur.fetchone() is None:
                c.execute("INSERT INTO jobs (run_id, status) VALUES (?, 'QUEUED')", (run_id,))
            sets = ", ".join(f"{k}=?" for k in fields)
            c.execute(f"UPDATE jobs SET {sets} WHERE run_id=?", (*fields.values(), run_id))

    def get(self, run_id: str) -> Optional[dict]:
        with self._conn() as c:
            c.row_factory = sqlite3.Row
            row = c.execute("SELECT * FROM jobs WHERE run_id=?", (run_id,)).fetchone()
            return dict(row) if row else None

    def all_jobs(self) -> list[dict]:
        with self._conn() as c:
            c.row_factory = sqlite3.Row
            return [dict(r) for r in c.execute("SELECT * FROM jobs")]


class FedMLAgent:
    """One worker agent bound to a spool directory."""

    def __init__(self, spool_dir: str, env: Optional[dict] = None):
        self.spool = Path(spool_dir)
        self.queue = self.spool / "queue"
        self.runs = self.spool / "runs"
        self.queue.mkdir(parents=True, exist_ok=True)
        self.runs.mkdir(parents=True, exist_ok=True)
        self.db = JobDB(str(self.spool / "jobs.sqlite"))
        self.env = env
        self._procs: dict[str, subprocess.Popen] = {}
        self._running = False

    # -- package pipeline (reference run_impl :480) --------------------------
    def process_package(self, pkg: Path) -> str:
        with zipfile.ZipFile(pkg) as z:
            manifest = json.loads(z.read("__fedml_job__.json"))
            run_id = manifest["run_id"]
            run_dir = self.runs / run_id
            run_dir.mkdir(parents=True, exist_ok=True)
            z.extractall(run_dir)
        pkg.unlink()  # claimed
        log_path = str(run_dir / "job.log")
        self.db.upsert(run_id, status="PROVISIONING", log_path=log_path)
        logf = open(log_path, "ab")
        env = dict(os.environ)
        if self.env:
            env.update(self.env)
        env["FEDML_RUN_ID"] = run_id
        # bootstrap (reference bootstrap :395)
        if manifest.get("bootstrap"):
            rc = subprocess.call(
                manifest["bootstrap"], shell=True, cwd=run_dir, stdout=logf, stderr=logf, env=env
            )
            if rc != 0:
                self.db.upsert(run_id, status="FAILED", returncode=rc, finished=time.time())
                logf.close()
                return run_id
        proc = subprocess.Popen(
            manifest["job"], shell=True, cwd=run_dir, stdout=logf, stderr=logf, env=env
        )
        self._procs[run_id] = proc
        self.db.upsert(run_id, status="RUNNING", pid=proc.pid, started=time.time())
        return run_id

    def sweep_once(self) -> list[str]:
        """One scheduling pass: claim queued packages + reap finished jobs
        (the JobMonitor role, ``job_monitor.py:337``)."""
        claimed = []
        for pkg in sorted(self.queue.glob("*.zip")):
            try:
                claimed.append(self.process_package(pkg))
            except FileNotFoundError:
                continue  # another agent claimed it
        for run_id, proc in list(self._procs.items()):
            rc = proc.poll()
            if rc is not None:
                self.db.upsert(
                    run_id,
                    status="FINISHED" if rc == 0 else "FAILED",
                    returncode=rc, finished=time.time(),
                )
                del self._procs[run_id]
        return claimed

    def run_forever(self, poll_s: float = 0.5) -> None:
        self._running = True
        while self._running:
            self.sweep_once()
            time.sleep(poll_s)

    def run_in_thread(self, poll_s: float = 0.5) -> threading.Thread:
        t = threading.Thread(target=self.run_forever, args=(poll_s,), daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        self._running = False
        for run_id, proc in self._procs.items():
            proc.terminate()
            self.db.upsert(run_id, status="UNDETERMINED")

    def wait_for(self, run_id: str, timeout: float = 120.0) -> dict:
        deadline = time.time() + timeout
        while time.time() < deadline:
            self.sweep_once()
            row = self.db.get(run_id)
            if row and row["status"] in ("FINISHED", "FAILED"):
                return row
            time.sleep(0.2)
        raise TimeoutError(f"job {run_id} did not finish in {timeout}s")

    def logs(self, run_id: str) -> str:
        row = self.db.get(run_id)
        if not row or not row.get("log_path"):
            return ""
        p = Path(row["log_path"])
        return p.read_text() if p.exists() else ""


def match_resources(jobs: list[dict], agents: list[dict]) -> dict[str, str]:
    """Minimal scheduler matcher (reference ``scheduler_matcher.py:6``): match
    each job's requested device count against agents' free devices,
    first-fit decreasing."""
    assignment: dict[str, str] = {}
    free = {a["id"]: int(a.get("num_devices", 1)) for a in agents}
    for job in sorted(jobs, key=lambda j: -int(j.get("computing", {}).get("minimum_num_gpus", 1))):
        need = int(job.get("computing", {}).get("minimum_num_gpus", 1))
        for aid, avail in sorted(free.items(), key=lambda kv: -kv[1]):
            if avail >= need:
                assignment[job["run_id"]] = aid
                free[aid] -= need
                break
    return assignment
