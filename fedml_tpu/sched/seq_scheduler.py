"""Runtime-fit + min-makespan workload scheduler (FedAvg_seq).

Parity with ``core/schedule/seq_train_scheduler.py:9`` +
``runtime_estimate.py:16``: the reference's fedavg_seq MPI platform assigns
each worker a SET of clients to train sequentially per round; it fits a
linear runtime model t = a*n_samples + b from observed per-(worker, client)
runtimes and searches client->worker assignments minimizing the makespan
(slowest worker's total).

TPU-native redesign:
- The reference's exact recursive search is exponential with pruning
  (``assign_a_workload_serial``); here the solver is LPT (longest processing
  time first — the classic 4/3-approximation) followed by pairwise-swap
  local search, which is deterministic, O(n log n + refinement), and within
  a few percent of optimal on ragged Dirichlet shard distributions.  An
  exact branch-and-bound is kept for small instances (n <= 12) so tests can
  certify optimality.
- Runtime fitting is a closed-form least-squares fit (no scipy), one model
  per device (heterogeneous pools) or shared (uniform pools), with the mean
  relative fit error reported like the reference's ``fit_error``.

Used by the hierarchical simulator (``sim/hierarchical.py``) to balance
total samples across client groups (the default ``group_assignment:
balanced`` mode); the flat mesh engine pads clients to a uniform capacity so
its jitted path is placement-invariant and needs no scheduling.
``RuntimeEstimator``/``balanced_client_order`` are public API for host-loop
and cross-silo placement planning.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

import numpy as np


def fit_linear_runtime(samples: Sequence[float], runtimes: Sequence[float]):
    """Least-squares fit t ~= a*n + b.  Returns (cost_fn, (a, b), rel_error)
    — reference ``linear_fit`` (runtime_estimate.py:4)."""
    x = np.asarray(samples, dtype=np.float64)
    y = np.asarray(runtimes, dtype=np.float64)
    if len(x) < 2 or np.allclose(x, x[0]):
        a, b = 0.0, float(y.mean()) if len(y) else 0.0
    else:
        a, b = np.polyfit(x, y, 1)
    pred = a * x + b
    rel_err = float(np.mean(np.abs(pred - y) / np.maximum(np.abs(y), 1e-12))) if len(y) else 0.0
    return (lambda n: max(float(a) * float(n) + float(b), 0.0)), (float(a), float(b)), rel_err


class RuntimeEstimator:
    """Accumulates observed (device, client, n_samples, runtime) tuples and
    fits per-device linear cost models — reference ``t_sample_fit``."""

    def __init__(self, uniform_devices: bool = True):
        self.uniform_devices = uniform_devices
        self._obs: dict[int, list[tuple[float, float]]] = {}

    def record(self, device_id: int, n_samples: float, runtime_s: float) -> None:
        key = 0 if self.uniform_devices else int(device_id)
        self._obs.setdefault(key, []).append((float(n_samples), float(runtime_s)))

    def cost_fns(self, n_devices: int):
        """One cost fn per device (shared when uniform).  Devices with no
        observations fall back to t = n (sample-count-proportional)."""
        fns, errs = [], []
        for d in range(n_devices):
            key = 0 if self.uniform_devices else d
            obs = self._obs.get(key, [])
            if obs:
                fn, _, err = fit_linear_runtime([o[0] for o in obs], [o[1] for o in obs])
            else:
                fn, err = (lambda n: float(n)), 0.0
            fns.append(fn)
            errs.append(err)
        return fns, errs


@dataclass
class Schedule:
    assignment: list[list[int]]  # per-device client-index lists
    loads: np.ndarray            # per-device total cost
    makespan: float
    iterations: int = 0


class SeqTrainScheduler:
    """Min-makespan assignment of client workloads to devices.

    ``workloads[i]`` is client i's sample count; ``cost_fns[d](n)`` that
    device's estimated runtime for n samples (default: identity).
    """

    def __init__(self, workloads: Sequence[float], n_devices: int,
                 cost_fns: Optional[Sequence[Callable[[float], float]]] = None):
        self.workloads = np.asarray(workloads, dtype=np.float64)
        self.n_devices = int(n_devices)
        if cost_fns is None:
            cost_fns = [lambda n: float(n)] * self.n_devices
        assert len(cost_fns) == self.n_devices
        self.cost_fns = list(cost_fns)
        # per-(device, client) cost matrix
        self.costs = np.array(
            [[fn(w) for w in self.workloads] for fn in self.cost_fns], dtype=np.float64
        )

    # -- solvers -------------------------------------------------------------
    def schedule_lpt(self) -> Schedule:
        """Longest-processing-time-first greedy + pairwise-move/swap local
        search."""
        order = np.argsort(-self.workloads, kind="stable")
        assignment: list[list[int]] = [[] for _ in range(self.n_devices)]
        loads = np.zeros(self.n_devices)
        iters = 0
        for ci in order:
            # place on the device whose load after placement is smallest
            after = loads + self.costs[:, ci]
            d = int(np.argmin(after))
            assignment[d].append(int(ci))
            loads[d] = after[d]
            iters += 1
        # local search: move/swap between the max-loaded device and others
        improved = True
        while improved:
            improved = False
            worst = int(np.argmax(loads))
            for ci in list(assignment[worst]):
                for d in range(self.n_devices):
                    if d == worst:
                        continue
                    new_worst = loads[worst] - self.costs[worst, ci]
                    new_d = loads[d] + self.costs[d, ci]
                    if max(new_worst, new_d) + 1e-12 < loads.max():
                        assignment[worst].remove(ci)
                        assignment[d].append(ci)
                        loads[worst] = new_worst
                        loads[d] = new_d
                        improved = True
                        iters += 1
                        break
                if improved:
                    break
        return Schedule(assignment, loads, float(loads.max()), iters)

    def schedule_exact(self) -> Schedule:
        """Branch-and-bound exact min-makespan (small n only) — the
        reference's search, with the LPT solution as the incumbent bound."""
        n = len(self.workloads)
        assert n <= 14, "exact search is exponential; use schedule_lpt()"
        best = self.schedule_lpt()
        best_makespan = best.makespan
        best_assign = [list(a) for a in best.assignment]
        order = np.argsort(-self.workloads, kind="stable")
        loads = np.zeros(self.n_devices)
        assign: list[list[int]] = [[] for _ in range(self.n_devices)]
        iters = 0

        def rec(k: int):
            nonlocal best_makespan, best_assign, iters
            if k == n:
                if loads.max() < best_makespan - 1e-12:
                    best_makespan = float(loads.max())
                    best_assign = [list(a) for a in assign]
                return
            ci = int(order[k])
            seen_loads = set()
            for d in range(self.n_devices):
                if loads[d] in seen_loads:  # symmetry pruning
                    continue
                seen_loads.add(loads[d])
                c = self.costs[d, ci]
                if loads[d] + c >= best_makespan - 1e-12:
                    continue  # bound
                loads[d] += c
                assign[d].append(ci)
                iters += 1
                rec(k + 1)
                assign[d].pop()
                loads[d] -= c
        rec(0)
        final_loads = np.zeros(self.n_devices)
        for d, members in enumerate(best_assign):
            for ci in members:
                final_loads[d] += self.costs[d, ci]
        return Schedule(best_assign, final_loads, best_makespan, iters)

    def schedule(self) -> Schedule:
        if len(self.workloads) <= 12:
            return self.schedule_exact()
        return self.schedule_lpt()


def balanced_client_order(sample_counts: np.ndarray, n_shards: int) -> np.ndarray:
    """Order sampled clients so that consecutive groups of m/n_shards land on
    mesh shards with balanced total samples (the mesh engine lays stacked
    clients out contiguously per device).

    Returns a permutation of arange(len(sample_counts)).  Groups are padded
    round-robin when len % n_shards != 0.
    """
    counts = np.asarray(sample_counts, dtype=np.float64)
    m = len(counts)
    sched = SeqTrainScheduler(counts, n_shards).schedule_lpt()
    per = -(-m // n_shards)
    order: list[int] = []
    # round-robin drain so every group has exactly `per` members (pad from
    # the least-loaded groups' tails)
    pools = [list(a) for a in sched.assignment]
    for d in range(n_shards):
        while len(pools[d]) < per:
            donor = int(np.argmax([len(p) for p in pools]))
            if donor == d or len(pools[donor]) <= per - 1:
                break
            pools[d].append(pools[donor].pop())
    for p in pools:
        order.extend(p[:per])
    seen = set(order)
    order.extend([i for i in range(m) if i not in seen])
    return np.asarray(order[:m], dtype=np.int64)
