"""Launch manager — package a job workspace and submit it.

Parity with ``computing/scheduler/scheduler_entry/launch_manager.py``
(``FedMLLaunchManager``): parse a job YAML with the reference's section
vocabulary (``workspace`` / ``job`` / ``bootstrap`` / ``computing``), build a
run package (zip of the workspace), and create a run.  The reference uploads
to S3 and dispatches over MQTT to agents; this build's transport is a local
spool directory (the zero-egress "local cluster"), with the same artifact
format — an agent on any shared filesystem consumes identical packages.
"""

from __future__ import annotations

import json
import os
import time
import uuid
import zipfile
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

import yaml


@dataclass
class JobSpec:
    """Reference job.yaml schema (launch examples: workspace, job command,
    bootstrap, computing resources)."""

    workspace: str
    job: str  # the entry command, e.g. "python main.py --cf fedml_config.yaml"
    bootstrap: str = ""  # setup script run before the job
    job_name: str = ""
    computing: dict = field(default_factory=dict)  # minimum_num_gpus etc.

    @classmethod
    def from_yaml(cls, path: str) -> "JobSpec":
        with open(path) as f:
            doc = yaml.safe_load(f) or {}
        missing = [k for k in ("workspace", "job") if k not in doc]
        if missing:
            raise ValueError(f"job yaml missing required keys {missing}")
        return cls(
            workspace=doc["workspace"],
            job=doc["job"],
            bootstrap=doc.get("bootstrap", ""),
            job_name=doc.get("job_name", ""),
            computing=doc.get("computing", {}) or {},
        )


class FedMLLaunchManager:
    def __init__(self, spool_dir: str):
        self.spool = Path(spool_dir)
        (self.spool / "queue").mkdir(parents=True, exist_ok=True)
        (self.spool / "runs").mkdir(parents=True, exist_ok=True)

    def build_package(self, spec: JobSpec, base_dir: str = ".") -> Path:
        """Zip the workspace + a manifest (the reference's run package)."""
        ws = Path(base_dir) / spec.workspace
        if not ws.is_dir():
            raise FileNotFoundError(f"workspace {ws} not found")
        run_id = f"run_{int(time.time())}_{uuid.uuid4().hex[:8]}"
        pkg = self.spool / "queue" / f"{run_id}.zip"
        with zipfile.ZipFile(pkg, "w", zipfile.ZIP_DEFLATED) as z:
            for p in sorted(ws.rglob("*")):
                if p.is_file():
                    z.write(p, p.relative_to(ws))
            manifest = {
                "run_id": run_id,
                "job": spec.job,
                "bootstrap": spec.bootstrap,
                "job_name": spec.job_name or run_id,
                "computing": spec.computing,
                "created": time.time(),
            }
            z.writestr("__fedml_job__.json", json.dumps(manifest))
        return pkg

    def launch_job(self, yaml_path: str) -> str:
        """``fedml launch job.yaml`` — returns the run_id."""
        spec = JobSpec.from_yaml(yaml_path)
        pkg = self.build_package(spec, base_dir=str(Path(yaml_path).parent))
        return pkg.stem

    def list_queue(self) -> list[str]:
        return sorted(p.stem for p in (self.spool / "queue").glob("*.zip"))
