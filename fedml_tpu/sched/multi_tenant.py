"""Multi-tenant federated control plane — pack N concurrent FL jobs onto one
fleet (ISSUE 14).

The reference framework's largest subsystem is its MLOps scheduler
(PAPER.md §L8, ~29.3k LoC: any machine becomes a launchable worker serving
many jobs); this repo's sched/ ran exactly ONE job at a time.  Production on
shared chips means many tenants per mesh, so this module adds the missing
layer: a control plane that

- **admits N concurrent FL jobs** (`admit`), each with its own isolated
  config (:func:`tenant_config` deep-copies the recipe, re-keys the in-proc
  fabric per job, and scopes every durable artifact under the job id);
- **gang-schedules their (virtual) rounds onto one mesh/host pool** at
  round boundaries through the :class:`~fedml_tpu.cross_silo.runtime.
  GangScheduler`: ``mt_slots`` rounds run at once, grants go by strict
  ``mt_priority`` then weighted fair share over the MEASURED round cost
  (``mt_weight``), and preemption happens only at boundaries — a running
  round is never aborted, a higher-priority job simply wins every
  subsequent grant;
- **isolates tenants end-to-end**: per-job journal roots
  (``<journal_root>/job_<id>/server`` and ``.../clients`` — the existing
  :class:`ServerJournal`/:class:`ClientJournal` machinery rides unchanged
  under the scoped path), per-job metric namespaces (a ``job`` label
  threaded through :meth:`MetricsRegistry.scoped` — colliding family names
  land in one family whose samples stay separated per job), and per-tenant
  flag isolation (each job reads only its own ``extra``);
- **shares ONE AOT program store** across tenants (``mt_shared_aot_dir``):
  job k+1 with the same tracing fingerprint DESERIALIZES job k's exported
  round/eval programs instead of recompiling — the FedJAX observation
  (PAPERS.md 2108.02117) that identically-shaped round programs are free
  warm starts, now across jobs.

All of it rides the event-driven server runtime extracted in
``cross_silo/runtime.py``: one shared timer wheel + dispatch loop serves
every tenant's server, so N jobs cost one loop thread, not N thread soups.
With the plane unused (no ``round_gate``, no ``mt_*`` flags) the single-job
sync and async server paths are bit-identical to before this module
existed — regression-pinned by tests/test_multi_tenant.py.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from typing import Optional

from ..core.flags import cfg_extra
from ..cross_silo.runtime import GangScheduler, ServerRuntime
from ..obs import registry as obsreg

log = logging.getLogger("fedml_tpu.sched.multi_tenant")

__all__ = ["MultiTenantControlPlane", "TenantJob", "tenant_config",
           "run_multi_tenant_soak"]

JOB_ROUNDS = obsreg.REGISTRY.gauge(
    "fedml_mt_job_rounds",
    "Rounds (sync) / virtual rounds (async) completed per tenant job.",
    labels=("job",),
)
JOBS_ADMITTED = obsreg.REGISTRY.counter(
    "fedml_mt_jobs_admitted_total",
    "Tenant jobs admitted by a multi-tenant control plane.",
)
AOT_WARM_JOBS = obsreg.REGISTRY.counter(
    "fedml_mt_shared_aot_warm_jobs_total",
    "Admitted jobs whose server programs resolved from the SHARED AOT "
    "store with at least one cross-job warm hit.",
)


def tenant_config(cfg, job_id: str, *, journal_root: Optional[str] = None,
                  aot_dir: Optional[str] = None):
    """One tenant's isolated config: a deep-copied recipe whose run_id,
    journal roots, publish dir, and metric namespace are scoped under
    ``job_<id>`` — reusing ServerJournal/ClientJournal/ModelPublisher
    unchanged underneath the per-job path.

    The returned config owns a FRESH ``extra`` dict: a tenant mutating its
    flags can never be observed by a sibling or by the admitted base
    recipe.  When ``journal_root`` is unset, any journal/publish dirs the
    base recipe carries are job-scoped in place (``<dir>/job_<id>``) so two
    tenants admitted from one recipe never interleave snapshots."""
    jid = str(job_id)
    overrides = {"mt_job_id": jid}

    def _scoped(base_dir: Optional[str], leaf: str) -> Optional[str]:
        if journal_root:
            return os.path.join(str(journal_root), f"job_{jid}", leaf)
        if base_dir:
            return os.path.join(str(base_dir), f"job_{jid}")
        return None

    sj = _scoped(cfg_extra(cfg, "server_journal_dir"), "server")
    if sj:
        overrides["server_journal_dir"] = sj
    cj = _scoped(cfg_extra(cfg, "client_journal_dir"), "clients")
    if cj:
        overrides["client_journal_dir"] = cj
    pub = cfg_extra(cfg, "model_publish_dir")
    if pub:
        overrides["model_publish_dir"] = os.path.join(str(pub), f"job_{jid}")
    # flight bundles (ISSUE 16): each tenant's black boxes land under its
    # own job dir, so one crashed tenant's postmortem never mixes with a
    # sibling's
    fd = cfg_extra(cfg, "flight_dir")
    if fd:
        overrides["flight_dir"] = os.path.join(str(fd), f"job_{jid}")
    # performance timeline (ISSUE 18): same isolation stance — each
    # tenant's segment files land under its own job dir (the samples
    # themselves stay distinguishable anyway via the job label the
    # ScopedRegistry stamps on every series)
    td = cfg_extra(cfg, "timeline_dir")
    if td:
        overrides["timeline_dir"] = os.path.join(str(td), f"job_{jid}")
    shared_aot = aot_dir or cfg_extra(cfg, "mt_shared_aot_dir")
    if shared_aot:
        overrides["aot_programs"] = True
        overrides["aot_programs_dir"] = str(shared_aot)
    new_extra = {**dict(getattr(cfg, "extra", None) or {}), **overrides}
    return dataclasses.replace(
        cfg, run_id=f"{getattr(cfg, 'run_id', '0')}_job_{jid}", extra=new_extra)


class TenantJob:
    """One admitted job: its isolated config, server, clients (real in-proc
    managers or a simulated fleet), and job-scoped metric view."""

    def __init__(self, job_id: str, cfg, dataset, model, server, clients,
                 weight: float, priority: int):
        self.job_id = job_id
        self.cfg = cfg
        self.dataset = dataset
        self.model = model
        self.server = server
        self.clients = list(clients)
        self.weight = weight
        self.priority = priority
        #: job-scoped registry view — every family registered through it
        #: carries job=<id>, so colliding names across tenants cannot bleed
        self.metrics = obsreg.REGISTRY.scoped(job=job_id)
        #: the submesh leased to this job (None = full-mesh time slicing)
        self.mesh = None
        self.fleet = None
        self._fleet_queue = None
        #: per-job AOT accounting delta captured at admit (shared-store
        #: warm starts show up as hits during server construction)
        self.aot_hits_at_admit = 0
        self.started_monotonic: Optional[float] = None
        self.finished_monotonic: Optional[float] = None

    @property
    def done(self) -> "threading.Event":
        return self.server.done

    def rounds_completed(self) -> int:
        return int(getattr(self.server, "server_version", None)
                   or len(self.server.history))

    def summary(self) -> dict:
        out = {
            "job_id": self.job_id,
            "weight": self.weight,
            "priority": self.priority,
            "rounds": self.rounds_completed(),
            "history_rows": len(self.server.history),
            "done": self.server.done.is_set(),
        }
        if self.started_monotonic and self.finished_monotonic:
            out["wall_s"] = round(self.finished_monotonic - self.started_monotonic, 4)
        if hasattr(self.server, "async_summary"):
            a = self.server.async_summary()
            out["server_version"] = a["server_version"]
            out["arrivals"] = a["arrivals"]
        return out


class MultiTenantControlPlane:
    """Admit → gang-schedule → run N FL jobs on one mesh/host pool.

    One shared :class:`ServerRuntime` (timer wheel + dispatch loop) serves
    every tenant's server; one :class:`GangScheduler` arbitrates the mesh
    slots.  ``slots``/``aot_dir`` default from the optional ``base_cfg``'s
    ``mt_slots``/``mt_shared_aot_dir`` flags (1 / unset without one).

    Thread model (GL008-audited): admit/start/run_until_done/close are
    driver-thread calls (the plane is built and driven from one thread, like
    the soak harnesses); all cross-thread state lives inside the runtime,
    the scheduler, and the servers, each with its own discipline.
    """

    def __init__(self, *, slots: Optional[int] = None,
                 journal_root: Optional[str] = None,
                 aot_dir: Optional[str] = None,
                 runtime: Optional[ServerRuntime] = None,
                 base_cfg=None, plan=None,
                 quota_burst: Optional[float] = None,
                 quota_refill_s: Optional[float] = None):
        #: optional SubmeshPlan (parallel/mesh.py): present — explicitly or
        #: via base_cfg's mt_submesh_shape/mt_submesh_jobs — each admitted
        #: job leases ONE disjoint submesh and rounds run genuinely
        #: concurrently; absent/rejected = the PR-14 time-sliced gate
        if plan is None and base_cfg is not None:
            from ..parallel.mesh import submesh_plan_from_config

            plan = submesh_plan_from_config(base_cfg)
        self.plan = plan
        self.slots = (len(plan) if plan is not None
                      else int(slots if slots is not None
                               else cfg_extra(base_cfg, "mt_slots")))
        self.journal_root = journal_root
        self.aot_dir = aot_dir or cfg_extra(base_cfg, "mt_shared_aot_dir")
        self.runtime = runtime if runtime is not None else ServerRuntime(
            name="fedml-mt-runtime")
        self._owns_runtime = runtime is None
        self.scheduler = GangScheduler(
            self.runtime, slots=self.slots, plan=plan,
            quota_burst=(quota_burst if quota_burst is not None
                         else cfg_extra(base_cfg, "mt_quota_burst")),
            quota_refill_s=(quota_refill_s if quota_refill_s is not None
                            else cfg_extra(base_cfg, "mt_quota_refill_s")))
        self.jobs: dict[str, TenantJob] = {}
        self._started = False

    # -- admission ------------------------------------------------------------
    def admit(self, cfg, *, job_id: Optional[str] = None,
              weight: Optional[float] = None, priority: Optional[int] = None,
              dataset=None, model=None, backend: str = "INPROC",
              build_clients: bool = True) -> TenantJob:
        """Admit one job: isolate its config, build its server (+ real
        in-proc clients unless ``build_clients=False`` — attach a simulated
        fleet instead via :meth:`attach_sim_fleet`), and register it with
        the gang scheduler.  Nothing runs until :meth:`start`."""
        from ..core.aot import AOT_HITS

        jid = str(job_id if job_id is not None
                  else (cfg_extra(cfg, "mt_job_id") or f"job{len(self.jobs)}"))
        if jid in self.jobs:
            raise ValueError(f"job id {jid!r} already admitted")
        w = float(weight if weight is not None else cfg_extra(cfg, "mt_weight"))
        prio = int(priority if priority is not None
                   else cfg_extra(cfg, "mt_priority"))
        tcfg = tenant_config(cfg, jid, journal_root=self.journal_root,
                             aot_dir=self.aot_dir)
        if dataset is None:
            from ..data import loader

            dataset = loader.load(tcfg)
        if model is None:
            from ..models import model_hub

            model = model_hub.create(tcfg, dataset.class_num)
        if backend == "INPROC":
            from ..comm.inproc import InProcRouter

            InProcRouter.reset(tcfg.run_id)
        from ..cross_silo import build_client, build_server

        clients = []
        if build_clients:
            clients = [build_client(tcfg, dataset, model, rank=r, backend=backend)
                       for r in range(1, tcfg.client_num_in_total + 1)]
        lease_idx = None
        lease_mesh = None
        if self.plan is not None:
            # static home lease: the job's compiled programs (shardings,
            # AOT fingerprints) bind to these devices for its lifetime
            lease_idx = len(self.jobs) % len(self.plan)
            lease_mesh = self.plan.lease(lease_idx)
        hits0 = AOT_HITS.value()
        server = build_server(tcfg, dataset, model, backend=backend,
                              runtime=self.runtime, mesh=lease_mesh)
        job = TenantJob(jid, tcfg, dataset, model, server, clients,
                        weight=w, priority=prio)
        job.mesh = lease_mesh
        job.aot_hits_at_admit = int(AOT_HITS.value() - hits0)
        if job.aot_hits_at_admit > 0:
            AOT_WARM_JOBS.inc()
        server.round_gate = self.scheduler
        self.scheduler.register(server, jid, weight=w, priority=prio,
                                lease_index=lease_idx)
        self.jobs[jid] = job
        JOBS_ADMITTED.inc()
        log.info("admitted job %s (weight %.2f, priority %d, %d clients, "
                 "aot warm hits at admit %d)", jid, w, prio, len(clients),
                 job.aot_hits_at_admit)
        return job

    def attach_sim_fleet(self, job: TenantJob, **fleet_kwargs) -> None:
        """Replace real clients with the event-scheduled simulated fleet
        (``cross_silo/async_soak.py``) for fleet-scale jobs — the bench's
        8-concurrent-jobs shape."""
        from ..cross_silo.async_soak import attach_sim_fleet

        job.fleet, job._fleet_queue = attach_sim_fleet(job.server, **fleet_kwargs)

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        """Launch every admitted job: client receive loops, server receive
        loops, then the status-discovery kick.  Rounds begin as the gang
        scheduler grants slots."""
        self._started = True
        for job in self.jobs.values():
            for c in job.clients:
                c.run_in_thread()
        for job in self.jobs.values():
            job.started_monotonic = time.monotonic()
            job.server.run_in_thread()
            job.server.start()

    def run_until_done(self, timeout: float = 600.0) -> dict:
        """Block until every job completes (or raise on timeout, naming the
        laggards); returns :meth:`summary`."""
        deadline = time.monotonic() + float(timeout)
        for jid, job in self.jobs.items():
            remaining = deadline - time.monotonic()
            if not job.server.done.wait(max(0.0, remaining)):
                laggards = [j for j, jb in self.jobs.items()
                            if not jb.server.done.is_set()]
                raise TimeoutError(
                    f"multi-tenant run did not finish in {timeout}s; "
                    f"pending jobs: {laggards}; scheduler: "
                    f"{self.scheduler.summary()}")
            if job.finished_monotonic is None:
                job.finished_monotonic = time.monotonic()
            JOB_ROUNDS.set(job.rounds_completed(), job=jid)
        return self.summary()

    def summary(self) -> dict:
        """Per-job completion + gang-scheduler accounting."""
        return {
            "slots": self.slots,
            "jobs": {jid: job.summary() for jid, job in self.jobs.items()},
            "scheduler": self.scheduler.summary(),
        }

    def close(self) -> None:
        """Tear every job down (idempotent): servers, clients, fleets,
        per-job fabrics, and the owned runtime."""
        from ..comm.inproc import InProcRouter

        for job in self.jobs.values():
            try:
                job.server.finish()
            except Exception:
                log.warning("job %s server teardown failed", job.job_id,
                            exc_info=True)
            for c in job.clients:
                try:
                    c.finish()
                except Exception:
                    log.warning("job %s client teardown failed", job.job_id,
                                exc_info=True)
            if job.fleet is not None:
                job.fleet.stop(job._fleet_queue)
                job.fleet = None
            InProcRouter.reset(job.cfg.run_id)
        if self._owns_runtime:
            self.runtime.close()


# ---------------------------------------------------------------------------
# bench / dryrun harness
# ---------------------------------------------------------------------------

def run_multi_tenant_soak(n_jobs: int = 8, versions: int = 6, *,
                          concurrent: bool = True, slots: int = 2,
                          clients_per_job: int = 32, concurrency: int = 8,
                          buffer_k: int = 8, latency_mean_s: float = 0.002,
                          latency_sigma: float = 1.0, seed: int = 0,
                          weights: Optional[list] = None,
                          priorities: Optional[list] = None,
                          journal_root: Optional[str] = None,
                          aot_dir: Optional[str] = None,
                          submesh_shape: Optional[str] = None,
                          extra_flags: Optional[dict] = None,
                          timeout_s: float = 600.0) -> dict:
    """N buffered-async jobs, each with its own simulated client fleet,
    gang-scheduled onto one host pool — or the SAME jobs run one at a time
    through the same gated machinery (``concurrent=False``, the Nx-sequential
    baseline the bench ratio divides by).

    ``submesh_shape`` (e.g. ``"clients:2"``): carve ``n_jobs`` disjoint
    submeshes and run the CONCURRENT leg as a fleet partition — every job
    leases its own devices and rounds genuinely overlap (the ``--mode
    fleet`` bench shape); the sequential baseline always runs on the full
    mesh.  Raises ``ValueError`` when the shapes don't tile the fleet.

    Returns aggregate versions/s, pooled p50/p95 round-hold latency (the
    per-round mesh occupancy under gang scheduling), and the per-job
    scheduler accounting."""
    import fedml_tpu

    from ..cross_silo.async_soak import _soak_config

    plan = None
    if concurrent and submesh_shape:
        from ..parallel import mesh as meshlib

        names, sizes = meshlib.parse_mesh_shape(submesh_shape)
        plan = meshlib.carve_submeshes(names, sizes, n_jobs)

    def _job_cfg(i: int):
        return _soak_config(
            f"mtsoak_{'c' if concurrent else 's'}_{seed}_{i}",
            clients_per_job, concurrency, buffer_k, versions,
            staleness_exponent=0.5, redispatch_timeout_s=2.0,
            extra_flags=extra_flags)

    def _run_plane(job_indices) -> tuple[float, list, dict]:
        plane = MultiTenantControlPlane(slots=slots, journal_root=journal_root,
                                        aot_dir=aot_dir, plan=plan)
        try:
            for i in job_indices:
                cfg = _job_cfg(i)
                fedml_tpu.init(cfg)
                job = plane.admit(
                    cfg, job_id=f"t{i}",
                    weight=(weights[i] if weights else None),
                    priority=(priorities[i] if priorities else None),
                    build_clients=False)
                plane.attach_sim_fleet(
                    job, drop_prob=0.0, latency_mean_s=latency_mean_s,
                    latency_sigma=latency_sigma, seed=seed + i, workers=2)
            t0 = time.monotonic()
            plane.start()
            plane.run_until_done(timeout=timeout_s)
            wall = time.monotonic() - t0
            holds = [h for rec in plane.scheduler.stats.values()
                     for h in rec["hold_s"]]
            return wall, holds, plane.summary()
        finally:
            plane.close()

    if concurrent:
        wall, holds, summary = _run_plane(list(range(n_jobs)))
        walls = [wall]
    else:
        wall = 0.0
        holds = []
        summaries = []
        walls = []
        for i in range(n_jobs):
            w, h, s = _run_plane([i])
            wall += w
            walls.append(w)
            holds.extend(h)
            summaries.append(s)
        summary = {"sequential_runs": summaries}

    import numpy as np

    total_versions = n_jobs * versions
    return {
        "mode": "concurrent" if concurrent else "sequential",
        "jobs": n_jobs,
        "slots": len(plan) if plan is not None else slots,
        "submesh": plan.describe() if plan is not None else None,
        "versions_per_job": versions,
        "versions_total": total_versions,
        "wall_s": round(wall, 4),
        "aggregate_versions_per_sec": round(total_versions / max(wall, 1e-9), 4),
        "round_hold_p50_s": (round(float(np.percentile(holds, 50)), 6)
                             if holds else None),
        "round_hold_p95_s": (round(float(np.percentile(holds, 95)), 6)
                             if holds else None),
        "rounds_granted": len(holds),
        "summary": summary,
    }
