"""Agent control plane — remote start/stop/status/OTA over the comm fabric.

Parity with the reference agent's MQTT control plane
(``computing/scheduler/slave/client_runner.py`` family: the MLOps platform
publishes start_run/stop_run to per-edge topics; the agent subscribes,
spools the package, reports status, and OTA-upgrades itself on command).

Here the same four verbs ride the repo's own comm layer (MQTT in-memory
fabric by default; any backend with a Message path works), so the control
plane is hermetically testable and transport-pluggable:

    START_RUN(package bytes)  -> write to the agent's spool queue (the agent
                                 claims it on its next sweep)
    STOP_RUN(run_id)          -> terminate the job process, mark KILLED
    STATUS()                  -> reply with the job DB rows
    OTA(package bytes, ver)   -> stage the new agent package + stamp a
                                 restart marker (the supervisor restarts the
                                 agent process; in-place code reload is
                                 deliberately NOT attempted)

Authentication: every verb (not just the package-bearing ones — STOP_RUN
kills jobs and STATUS leaks the job DB) carries an HMAC-SHA256 over
(verb, target edge, identifier, timestamp, package bytes) keyed by the
shared ``control_plane_secret``, with a freshness window so captured
messages cannot be replayed later (e.g. re-staging an old OTA package as a
downgrade attack).  Without a configured secret, only the in-proc fabric —
same process, inherently trusted — is accepted; a routable transport
without a secret refuses every verb.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import logging
import re
import time
from typing import Optional

log = logging.getLogger("fedml_tpu.sched.control_plane")

from .. import constants as _C
from ..comm.comm_manager import FedMLCommManager
from ..comm.message import Message
from .agent import FedMLAgent

MSG_TYPE_START_RUN = 40
MSG_TYPE_STOP_RUN = 41
MSG_TYPE_STATUS_REQUEST = 42
MSG_TYPE_STATUS_REPLY = 43
MSG_TYPE_OTA = 44

KEY_PACKAGE = "package"
KEY_RUN_ID = "cp_run_id"
KEY_JOBS = "jobs"
KEY_VERSION = "agent_version"
KEY_SIGNATURE = "cp_signature"
KEY_TIMESTAMP = "cp_ts"

# replayed control messages older than this are rejected (bounds the replay
# surface without a per-message nonce store)
FRESHNESS_WINDOW_S = 300.0

_SAFE_NAME = re.compile(r"^[A-Za-z0-9._-]{1,128}$")


def _verb_signature(secret: str, verb: int, edge_id: int, name: str,
                    ts: str, package: bytes = b"", sender: int = 0) -> str:
    """HMAC-SHA256 binding verb + sender + recipient + identifier +
    timestamp + package bytes to the shared secret."""
    mac = hmac.new(secret.encode(), digestmod=hashlib.sha256)
    for part in (str(verb), str(sender), str(edge_id), name, ts):
        mac.update(part.encode())
        mac.update(b"\x00")
    mac.update(package)
    return mac.hexdigest()


def _check_signature(secret: str, msg: Message, verb: int, edge_id: int,
                     name: str, package: bytes = b"", sender: int = 0) -> None:
    """Single verification path for BOTH directions (requests and the status
    reply): freshness window, then constant-time MAC compare. Raises
    ValueError on any failure."""
    ts = str(msg.get(KEY_TIMESTAMP, ""))
    try:
        age = abs(time.time() - float(ts))
    except ValueError:
        raise ValueError(f"missing/invalid timestamp on verb {verb}")
    if not (age <= FRESHNESS_WINDOW_S):  # rejects NaN too
        raise ValueError(f"stale control-plane message (age {age:.0f}s) on verb {verb}")
    got = str(msg.get(KEY_SIGNATURE, ""))
    want = _verb_signature(secret, verb, edge_id, name, ts, package, sender)
    if not hmac.compare_digest(got, want):
        raise ValueError(f"bad control-plane signature on verb {verb}")


def _safe_name(value, what: str) -> str:
    """Remote-controlled identifiers become filename components; anything
    with separators ('../../x') is an arbitrary-path write on an open
    transport — refuse it."""
    name = str(value)
    if not _SAFE_NAME.match(name) or name in (".", ".."):
        raise ValueError(f"unsafe {what} {name!r} from control plane")
    return name


class AgentControlPlane(FedMLCommManager):
    """Rank = agent's edge id; the controller (rank 0) sends verbs."""

    def __init__(self, cfg, agent: FedMLAgent, rank: int, backend: Optional[str] = None):
        super().__init__(cfg, rank=rank, size=0, backend=backend)
        self.agent = agent
        self.ota_dir = agent.spool / "ota"
        self.secret: Optional[str] = getattr(cfg, "control_plane_secret", None)
        # Prometheus exposition for the agent host (scrape comm/job metrics
        # without the SaaS the reference requires): extra['metrics_port']
        from ..obs import registry as obsreg

        self.metrics_server = obsreg.maybe_start_metrics_server(cfg)

    def finish(self) -> None:
        super().finish()
        if self.metrics_server is not None:
            self.metrics_server.close()
            self.metrics_server = None

    def _verify(self, msg: Message, verb: int, name: str, package: bytes = b"") -> None:
        """Reject any verb whose HMAC or freshness fails; see module doc."""
        if self.secret is None:
            if self.backend != _C.COMM_BACKEND_INPROC:
                raise ValueError(
                    f"unauthenticated verb {verb} on routable backend {self.backend!r}: "
                    "configure control_plane_secret"
                )
            return
        _check_signature(self.secret, msg, verb, self.rank, name, package)

    @staticmethod
    def _package_bytes(msg: Message) -> bytes:
        """Attacker-controlled field: a missing/mistyped package must become a
        rejection, not an uncaught TypeError in the receive loop."""
        import numpy as np

        raw = msg.get(KEY_PACKAGE)
        if raw is None:
            raise ValueError("missing package")
        try:
            return bytes(np.asarray(raw, dtype=np.uint8))
        except (TypeError, ValueError) as e:
            raise ValueError(f"malformed package: {e}")

    def register_message_receive_handlers(self) -> None:
        # a malformed/hostile message must be REJECTED, not allowed to kill
        # the receive loop (the observer loop does not catch handler errors);
        # anything a hostile sender can trigger — not just ValueError — must
        # be contained here
        def guarded(handler):
            def wrapper(msg: Message) -> None:
                try:
                    handler(msg)
                except Exception as e:
                    log.warning("control-plane message rejected: %s", e)
            return wrapper

        self.register_message_receive_handler(MSG_TYPE_START_RUN, guarded(self.handle_start_run))
        self.register_message_receive_handler(MSG_TYPE_STOP_RUN, guarded(self.handle_stop_run))
        self.register_message_receive_handler(MSG_TYPE_STATUS_REQUEST, guarded(self.handle_status))
        self.register_message_receive_handler(MSG_TYPE_OTA, guarded(self.handle_ota))

    def handle_start_run(self, msg: Message) -> None:
        pkg_bytes = self._package_bytes(msg)
        run_id = _safe_name(msg.get(KEY_RUN_ID), "run_id")
        self._verify(msg, MSG_TYPE_START_RUN, run_id, pkg_bytes)
        dest = self.agent.queue / f"{run_id}.zip"
        dest.write_bytes(pkg_bytes)
        self.agent.db.upsert(run_id, status="QUEUED")

    def handle_stop_run(self, msg: Message) -> None:
        run_id = _safe_name(msg.get(KEY_RUN_ID), "run_id")
        self._verify(msg, MSG_TYPE_STOP_RUN, run_id)
        # a stop that races the sweep: remove a still-queued package so the
        # next sweep cannot launch the supposedly-stopped job
        queued = self.agent.queue / f"{run_id}.zip"
        if queued.exists():
            queued.unlink()
        proc = self.agent._procs.pop(run_id, None)  # sweeps must not re-reap
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except Exception:
                proc.kill()
        self.agent.db.upsert(run_id, status="KILLED", finished=time.time())

    def handle_status(self, msg: Message) -> None:
        self._verify(msg, MSG_TYPE_STATUS_REQUEST, "")
        reply = Message(MSG_TYPE_STATUS_REPLY, self.rank, msg.get_sender_id())
        jobs_json = json.dumps(self.agent.db.all_jobs())
        reply.add_params(KEY_JOBS, jobs_json)
        if self.secret is not None:
            ts = repr(time.time())
            reply.add_params(KEY_TIMESTAMP, ts)
            # sender=self.rank binds the replying agent's identity: a signed
            # reply from agent A replayed with the sender field rewritten to
            # agent B must not verify
            reply.add_params(
                KEY_SIGNATURE,
                _verb_signature(self.secret, MSG_TYPE_STATUS_REPLY, msg.get_sender_id(),
                                jobs_json, ts, sender=self.rank),
            )
        self.send_message(reply)

    def handle_ota(self, msg: Message) -> None:
        """Stage the new agent package; a supervisor (systemd/k8s restart
        policy) picks up the marker — reference's OTA upgrade path
        (client_runner ota_upgrade) minus the in-place pip install."""
        version = _safe_name(msg.get(KEY_VERSION, "unknown"), "agent_version")
        pkg_bytes = self._package_bytes(msg)
        self._verify(msg, MSG_TYPE_OTA, version, pkg_bytes)
        self.ota_dir.mkdir(parents=True, exist_ok=True)
        pkg = self.ota_dir / f"agent-{version}.zip"
        pkg.write_bytes(pkg_bytes)
        (self.ota_dir / "RESTART_REQUIRED").write_text(
            json.dumps({"version": version, "package": str(pkg), "ts": time.time()})
        )


class AgentController(FedMLCommManager):
    """The MLOps-platform role: sends verbs to agents, collects status."""

    def __init__(self, cfg, backend: Optional[str] = None):
        super().__init__(cfg, rank=0, size=0, backend=backend)
        self.status_replies: dict[int, list[dict]] = {}
        self.secret: Optional[str] = getattr(cfg, "control_plane_secret", None)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(MSG_TYPE_STATUS_REPLY, self._handle_status_reply)

    def _handle_status_reply(self, msg: Message) -> None:
        # replies are attacker-observable/forgeable on routable transports:
        # verify the agent's signature (it binds the reply body, the sending
        # agent, and this controller) and contain malformed payloads instead
        # of killing the receive loop
        try:
            jobs_json = str(msg.get(KEY_JOBS, ""))
            if self.secret is None:
                # same policy as the agent side: no secret -> in-proc only
                if self.backend != _C.COMM_BACKEND_INPROC:
                    raise ValueError(
                        f"unauthenticated status reply on routable backend {self.backend!r}"
                    )
            else:
                _check_signature(self.secret, msg, MSG_TYPE_STATUS_REPLY, self.rank,
                                 jobs_json, sender=msg.get_sender_id())
            self.status_replies[msg.get_sender_id()] = json.loads(jobs_json)  # graftlint: disable=GL008(single-writer receive loop publishes a fully-built value; wait_status only polls dict.get, and a CPython dict store is an atomic publish)
        except Exception as e:
            log.warning("status reply rejected: %s", e)

    def _sign(self, msg: Message, verb: int, edge_id: int, name: str,
              package: bytes = b"") -> None:
        if self.secret is None:
            return
        ts = repr(time.time())
        msg.add_params(KEY_TIMESTAMP, ts)
        msg.add_params(KEY_SIGNATURE, _verb_signature(self.secret, verb, edge_id, name, ts, package))

    def _package_msg(self, msg_type: int, edge_id: int, package_bytes: bytes) -> Message:
        import numpy as np

        msg = Message(msg_type, 0, edge_id)
        msg.add_params(KEY_PACKAGE, np.frombuffer(package_bytes, dtype=np.uint8).copy())
        return msg

    def start_run(self, edge_id: int, run_id: str, package_bytes: bytes) -> None:
        msg = self._package_msg(MSG_TYPE_START_RUN, edge_id, package_bytes)
        msg.add_params(KEY_RUN_ID, run_id)
        self._sign(msg, MSG_TYPE_START_RUN, edge_id, run_id, package_bytes)
        self.send_message(msg)

    def stop_run(self, edge_id: int, run_id: str) -> None:
        msg = Message(MSG_TYPE_STOP_RUN, 0, edge_id)
        msg.add_params(KEY_RUN_ID, run_id)
        self._sign(msg, MSG_TYPE_STOP_RUN, edge_id, run_id)
        self.send_message(msg)

    def request_status(self, edge_id: int) -> None:
        msg = Message(MSG_TYPE_STATUS_REQUEST, 0, edge_id)
        self._sign(msg, MSG_TYPE_STATUS_REQUEST, edge_id, "")
        self.send_message(msg)

    def push_ota(self, edge_id: int, version: str, package_bytes: bytes) -> None:
        msg = self._package_msg(MSG_TYPE_OTA, edge_id, package_bytes)
        msg.add_params(KEY_VERSION, version)
        self._sign(msg, MSG_TYPE_OTA, edge_id, version, package_bytes)
        self.send_message(msg)

    def wait_status(self, edge_id: int, timeout: float = 10.0) -> Optional[list[dict]]:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if edge_id in self.status_replies:
                return self.status_replies.pop(edge_id)
            time.sleep(0.05)
        return None
