"""Agent control plane — remote start/stop/status/OTA over the comm fabric.

Parity with the reference agent's MQTT control plane
(``computing/scheduler/slave/client_runner.py`` family: the MLOps platform
publishes start_run/stop_run to per-edge topics; the agent subscribes,
spools the package, reports status, and OTA-upgrades itself on command).

Here the same four verbs ride the repo's own comm layer (MQTT in-memory
fabric by default; any backend with a Message path works), so the control
plane is hermetically testable and transport-pluggable:

    START_RUN(package bytes)  -> write to the agent's spool queue (the agent
                                 claims it on its next sweep)
    STOP_RUN(run_id)          -> terminate the job process, mark KILLED
    STATUS()                  -> reply with the job DB rows
    OTA(package bytes, ver)   -> stage the new agent package + stamp a
                                 restart marker (the supervisor restarts the
                                 agent process; in-place code reload is
                                 deliberately NOT attempted)
"""

from __future__ import annotations

import json
import logging
import time
from pathlib import Path
from typing import Optional

log = logging.getLogger("fedml_tpu.sched.control_plane")

from ..comm.comm_manager import FedMLCommManager
from ..comm.message import Message
from .agent import FedMLAgent

import re

MSG_TYPE_START_RUN = 40
MSG_TYPE_STOP_RUN = 41
MSG_TYPE_STATUS_REQUEST = 42
MSG_TYPE_STATUS_REPLY = 43
MSG_TYPE_OTA = 44

KEY_PACKAGE = "package"
KEY_RUN_ID = "cp_run_id"
KEY_JOBS = "jobs"
KEY_VERSION = "agent_version"

_SAFE_NAME = re.compile(r"^[A-Za-z0-9._-]{1,128}$")


def _safe_name(value, what: str) -> str:
    """Remote-controlled identifiers become filename components; anything
    with separators ('../../x') is an arbitrary-path write on an open
    transport — refuse it."""
    name = str(value)
    if not _SAFE_NAME.match(name) or name in (".", ".."):
        raise ValueError(f"unsafe {what} {name!r} from control plane")
    return name


class AgentControlPlane(FedMLCommManager):
    """Rank = agent's edge id; the controller (rank 0) sends verbs."""

    def __init__(self, cfg, agent: FedMLAgent, rank: int, backend: Optional[str] = None):
        super().__init__(cfg, rank=rank, size=0, backend=backend)
        self.agent = agent
        self.ota_dir = agent.spool / "ota"

    def register_message_receive_handlers(self) -> None:
        # a malformed/hostile message must be REJECTED, not allowed to kill
        # the receive loop (the observer loop does not catch handler errors)
        def guarded(handler):
            def wrapper(msg: Message) -> None:
                try:
                    handler(msg)
                except ValueError as e:
                    log.warning("control-plane message rejected: %s", e)
            return wrapper

        self.register_message_receive_handler(MSG_TYPE_START_RUN, guarded(self.handle_start_run))
        self.register_message_receive_handler(MSG_TYPE_STOP_RUN, guarded(self.handle_stop_run))
        self.register_message_receive_handler(MSG_TYPE_STATUS_REQUEST, guarded(self.handle_status))
        self.register_message_receive_handler(MSG_TYPE_OTA, guarded(self.handle_ota))

    def handle_start_run(self, msg: Message) -> None:
        import numpy as np

        pkg_bytes = bytes(np.asarray(msg.get(KEY_PACKAGE), dtype=np.uint8))
        run_id = _safe_name(msg.get(KEY_RUN_ID), "run_id")
        dest = self.agent.queue / f"{run_id}.zip"
        dest.write_bytes(pkg_bytes)
        self.agent.db.upsert(run_id, status="QUEUED")

    def handle_stop_run(self, msg: Message) -> None:
        run_id = _safe_name(msg.get(KEY_RUN_ID), "run_id")
        # a stop that races the sweep: remove a still-queued package so the
        # next sweep cannot launch the supposedly-stopped job
        queued = self.agent.queue / f"{run_id}.zip"
        if queued.exists():
            queued.unlink()
        proc = self.agent._procs.pop(run_id, None)  # sweeps must not re-reap
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except Exception:
                proc.kill()
        self.agent.db.upsert(run_id, status="KILLED", finished=time.time())

    def handle_status(self, msg: Message) -> None:
        reply = Message(MSG_TYPE_STATUS_REPLY, self.rank, msg.get_sender_id())
        reply.add_params(KEY_JOBS, json.dumps(self.agent.db.all_jobs()))
        self.send_message(reply)

    def handle_ota(self, msg: Message) -> None:
        """Stage the new agent package; a supervisor (systemd/k8s restart
        policy) picks up the marker — reference's OTA upgrade path
        (client_runner ota_upgrade) minus the in-place pip install."""
        import numpy as np

        self.ota_dir.mkdir(parents=True, exist_ok=True)
        version = _safe_name(msg.get(KEY_VERSION, "unknown"), "agent_version")
        pkg = self.ota_dir / f"agent-{version}.zip"
        pkg.write_bytes(bytes(np.asarray(msg.get(KEY_PACKAGE), dtype=np.uint8)))
        (self.ota_dir / "RESTART_REQUIRED").write_text(
            json.dumps({"version": version, "package": str(pkg), "ts": time.time()})
        )


class AgentController(FedMLCommManager):
    """The MLOps-platform role: sends verbs to agents, collects status."""

    def __init__(self, cfg, backend: Optional[str] = None):
        super().__init__(cfg, rank=0, size=0, backend=backend)
        self.status_replies: dict[int, list[dict]] = {}

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(MSG_TYPE_STATUS_REPLY, self._handle_status_reply)

    def _handle_status_reply(self, msg: Message) -> None:
        self.status_replies[msg.get_sender_id()] = json.loads(msg.get(KEY_JOBS))

    def _package_msg(self, msg_type: int, edge_id: int, package_bytes: bytes) -> Message:
        import numpy as np

        msg = Message(msg_type, 0, edge_id)
        msg.add_params(KEY_PACKAGE, np.frombuffer(package_bytes, dtype=np.uint8).copy())
        return msg

    def start_run(self, edge_id: int, run_id: str, package_bytes: bytes) -> None:
        msg = self._package_msg(MSG_TYPE_START_RUN, edge_id, package_bytes)
        msg.add_params(KEY_RUN_ID, run_id)
        self.send_message(msg)

    def stop_run(self, edge_id: int, run_id: str) -> None:
        msg = Message(MSG_TYPE_STOP_RUN, 0, edge_id)
        msg.add_params(KEY_RUN_ID, run_id)
        self.send_message(msg)

    def request_status(self, edge_id: int) -> None:
        self.send_message(Message(MSG_TYPE_STATUS_REQUEST, 0, edge_id))

    def push_ota(self, edge_id: int, version: str, package_bytes: bytes) -> None:
        msg = self._package_msg(MSG_TYPE_OTA, edge_id, package_bytes)
        msg.add_params(KEY_VERSION, version)
        self.send_message(msg)

    def wait_status(self, edge_id: int, timeout: float = 10.0) -> Optional[list[dict]]:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if edge_id in self.status_replies:
                return self.status_replies.pop(edge_id)
            time.sleep(0.05)
        return None
