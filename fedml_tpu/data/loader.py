"""Dataset loading dispatch.

Parity with the reference's ``data/data_loader.py:234`` (``load(args)``
dispatching on ``args.dataset`` at ``:262-530``).  Each loader first looks for
the real dataset files under ``data_cache_dir`` (same on-disk formats the
reference downloads: CIFAR python pickle batches, MNIST idx files, LEAF json);
when absent and ``synthetic_fallback`` is on, it generates a **deterministic
class-structured synthetic stand-in** with the same shapes/cardinalities, so
every recipe runs hermetically (zero-egress environments, CI).

Returns a :class:`~fedml_tpu.data.dataset.FederatedDataset`; use
``as_reference_tuple`` for the reference's 8-tuple API shape.
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import zlib
from pathlib import Path

import numpy as np

log = logging.getLogger("fedml_tpu.data.loader")

from ..arguments import Config
from ..core.flags import cfg_extra
from . import partition as part
from .dataset import FederatedDataset

_DATASET_SPECS = {
    # name: (feat shape, classes, default train size, default test size)
    "mnist": ((28, 28, 1), 10, 60000, 10000),
    "fashionmnist": ((28, 28, 1), 10, 60000, 10000),
    "femnist": ((28, 28, 1), 62, 60000, 10000),
    "cifar10": ((32, 32, 3), 10, 50000, 10000),
    "cifar100": ((32, 32, 3), 100, 50000, 10000),
    "cinic10": ((32, 32, 3), 10, 90000, 90000),
    "synthetic": ((60,), 10, 20000, 4000),
    # low-SNR benchmark: multi-modal gaussian cluster mixture whose accuracy
    # is center-estimation-limited — earned gradually, never saturating
    # early (SURVEY §7 hard-part 3 evidence; see _synthetic_hard)
    "synthetic_hard": ((32, 32, 3), 10, 20000, 4000),
    # federated Google Landmarks (reference data/fed_gld/data_loader.py):
    # 23k/160k images over 203/2028 landmark classes, resized 96x96
    "gld23k": ((96, 96, 3), 203, 23080, 2316),
    "gld160k": ((96, 96, 3), 2028, 164172, 14663),
    # StackOverflow tag prediction as bag-of-words logistic regression
    # (reference data/stackoverflow_lr/data_loader.py: 10k vocab, 500 tags)
    "stackoverflow_lr": ((10000,), 500, 50000, 10000),
    # Lending Club loan-status table (reference VFL finance example)
    "lending_club": ((200,), 2, 50000, 10000),
    # ImageNet class-per-directory layout (reference data_loader.py:375
    # ILSVRC2012; real sizes are read from disk, the spec seeds the fallback)
    "ilsvrc2012": ((224, 224, 3), 1000, 1281167, 50000),
    # UCI tables (reference data/UCI/data_loader_for_susy_and_ro.py)
    "susy": ((18,), 2, 100000, 20000),
    "room_occupancy": ((5,), 2, 8143, 2665),
    # NUS-WIDE 634-dim low-level features, top-5 single-label selection
    # (reference data/NUS_WIDE/nus_wide_dataset.py)
    "nus_wide": ((634,), 5, 60000, 40000),
    # FeTS2021 tumor-segmentation volumes (reference data/FeTS2021/; masks
    # ride FederatedDataset.masks for the FedSeg simulator)
    "fets2021": ((64, 64, 4), 4, 2000, 400),
}

# name normalization for reference spellings
_DATASET_ALIASES = {"imagenet": "ilsvrc2012", "ilsvrc-2012": "ilsvrc2012"}

_TEXT_SPECS = {
    # name: (seq len, vocab)
    "shakespeare": (80, 90),
    "fed_shakespeare": (80, 90),
    "stackoverflow_nwp": (20, 10004),
    # reddit next-word prediction (reference data/reddit/data_loader.py)
    "reddit": (20, 10000),
}


def dataset_spec(name: str):
    """Public accessor for a dense dataset's (feat_shape, classes, n_train,
    n_test) spec, applying the same name normalization as :func:`load`;
    None for text/unknown datasets.  Consumers (model_hub's small-input stem
    selection) must use this, not the private table, so the normalization
    contract lives in one place."""
    n = name.lower()
    return _DATASET_SPECS.get(_DATASET_ALIASES.get(n, n))


def load(cfg: Config) -> FederatedDataset:
    name = cfg.dataset.lower()
    name = _DATASET_ALIASES.get(name, name)
    if name == "fets2021":
        return _load_fets(cfg)
    if name == "synthetic_condshift":
        return _load_condshift(cfg)
    if name in _DATASET_SPECS:
        ds = _load_image_like(cfg, name)
    elif name in _TEXT_SPECS:
        ds = _load_text_like(cfg, name)
    else:
        raise ValueError(f"unknown dataset {cfg.dataset!r}")
    return ds


# ---------------------------------------------------------------------------
# image-like (dense feature) datasets
# ---------------------------------------------------------------------------

def _load_image_like(cfg: Config, name: str) -> FederatedDataset:
    feat, classes, n_train, n_test = _DATASET_SPECS[name]
    cache = Path(os.path.expanduser(cfg.data_cache_dir))
    arrays = _try_load_real(name, cache)
    if arrays is None:
        if not cfg.synthetic_fallback:
            raise FileNotFoundError(f"{name} not found under {cache} and synthetic_fallback=False")
        n_train = cfg.synthetic_train_size or n_train
        n_test = cfg.synthetic_test_size or n_test
        # cap the stand-in at ~2e8 float32 elements (~800 MB): gld160k's
        # real-size default (164k x 96x96x3 ≈ 18 GB + temporaries) would OOM
        # the host, and a synthetic stand-in gains nothing from that scale
        feat_elems = int(np.prod(feat))
        cap = max(1, int(2e8) // max(feat_elems, 1))
        if n_train > cap:
            log.warning("%s synthetic fallback capped at %d samples (was %d)", name, cap, n_train)
            n_train = cap
        # test set capped independently (a spec-default test set can be the
        # OOM source even when the train size was set small explicitly)
        test_cap = max(cap // 5, 1)
        if n_test > test_cap:
            log.warning("%s synthetic test set capped at %d samples (was %d)", name, test_cap, n_test)
            n_test = test_cap
        if name == "synthetic_hard":
            arrays = _synthetic_hard(feat, classes, n_train, n_test, cfg.random_seed)
        else:
            arrays = _synthetic_classification(name, feat, classes, n_train, n_test, cfg.random_seed)
    train_x, train_y, test_x, test_y = arrays
    idx_map = part.partition(
        cfg.partition_method, train_y, cfg.client_num_in_total, cfg.partition_alpha, cfg.random_seed
    )
    return FederatedDataset(
        train_x=train_x, train_y=train_y, test_x=test_x, test_y=test_y,
        client_idx=idx_map, class_num=classes, name=name,
    )


def _load_fets(cfg: Config) -> FederatedDataset:
    """FeTS2021: segmentation volumes + masks.  ``train_y`` carries each
    sample's dominant tissue class (what the Dirichlet partitioner and the
    classification-style eval consume); the full masks ride
    ``FederatedDataset.masks`` for the FedSeg simulator."""
    from . import extra_loaders

    feat, classes, n_train, n_test = _DATASET_SPECS["fets2021"]
    cache = Path(os.path.expanduser(cfg.data_cache_dir))
    try:
        x, m, tx, tm = extra_loaders.load_fets2021(cache / "FeTS2021")
    except (FileNotFoundError, OSError):
        if not cfg.synthetic_fallback:
            raise FileNotFoundError(
                f"fets2021_prepared.npz not found under {cache}/FeTS2021 and synthetic_fallback=False"
            )
        n_train = cfg.synthetic_train_size or n_train
        n_test = cfg.synthetic_test_size or n_test
        x, m, tx, tm = extra_loaders.synthesize_fets_like(
            n_train, n_test, cfg.random_seed, hw=feat[0], modalities=feat[2], classes=classes
        )

    def dominant(masks):
        out = np.zeros(len(masks), np.int32)
        for i, mk in enumerate(masks):
            fg = mk[mk > 0]
            out[i] = np.bincount(fg).argmax() if fg.size else 0
        return out

    y, ty = dominant(m), dominant(tm)
    idx_map = part.partition(
        cfg.partition_method, y, cfg.client_num_in_total, cfg.partition_alpha, cfg.random_seed
    )
    return FederatedDataset(
        train_x=x, train_y=y, test_x=tx, test_y=ty, client_idx=idx_map,
        class_num=int(max(m.max(), tm.max())) + 1, name="fets2021",
        masks=m, test_masks=tm,
    )


def _try_load_real(name: str, cache: Path):
    try:
        if name == "cifar10":
            d = cache / "cifar-10-batches-py"
            if d.is_dir():
                return _load_cifar_batches(d, ["data_batch_%d" % i for i in range(1, 6)], ["test_batch"], "labels")
        if name == "cifar100":
            d = cache / "cifar-100-python"
            if d.is_dir():
                return _load_cifar_batches(d, ["train"], ["test"], "fine_labels")
        if name in ("mnist", "fashionmnist"):
            d = cache / name.upper() / "raw" if (cache / name.upper()).is_dir() else cache / name
            if (d / "train-images-idx3-ubyte").exists():
                return _load_idx(d)
        from . import extra_loaders

        if name == "ilsvrc2012":
            for sub in ("ILSVRC2012", "imagenet", "."):
                root = cache / sub
                if (root / "train").is_dir():
                    tx_, ty_, vx_, vy_, _classes = extra_loaders.load_image_folder(root)
                    return tx_, ty_, vx_, vy_
        if name == "susy" and (cache / "SUSY" / "SUSY.csv").exists():
            return extra_loaders.load_susy(cache / "SUSY")
        if name == "room_occupancy" and (cache / "room_occupancy" / "datatraining.txt").exists():
            return extra_loaders.load_room_occupancy(cache / "room_occupancy")
        if name == "nus_wide" and (cache / "NUS_WIDE").is_dir():
            return extra_loaders.load_nus_wide(cache / "NUS_WIDE")
    except Exception:
        # a present-but-unreadable real dataset must be LOUD: silently
        # flipping to the synthetic stand-in would let a run proceed on fake
        # data while the user believes the real files were loaded
        log.exception(
            "real dataset %r found under %s but failed to load — falling "
            "back to the synthetic stand-in", name, cache,
        )
        return None
    return None


def _load_cifar_batches(d: Path, train_files, test_files, label_key):
    def load_batch(fname):
        with open(d / fname, "rb") as f:
            batch = pickle.load(f, encoding="bytes")
        x = batch[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1).astype(np.float32) / 255.0
        y = np.array(batch[label_key.encode()], dtype=np.int32)
        return x, y

    xs, ys = zip(*[load_batch(f) for f in train_files])
    txs, tys = zip(*[load_batch(f) for f in test_files])
    mean = np.array([0.4914, 0.4822, 0.4465], np.float32)
    std = np.array([0.2470, 0.2435, 0.2616], np.float32)
    train_x = (np.concatenate(xs) - mean) / std
    test_x = (np.concatenate(txs) - mean) / std
    return train_x, np.concatenate(ys), test_x, np.concatenate(tys)


def _load_idx(d: Path):
    def read_images(p):
        with open(p, "rb") as f:
            data = f.read()
        n = int.from_bytes(data[4:8], "big")
        arr = np.frombuffer(data, np.uint8, offset=16).reshape(n, 28, 28, 1)
        return arr.astype(np.float32) / 255.0

    def read_labels(p):
        with open(p, "rb") as f:
            data = f.read()
        return np.frombuffer(data, np.uint8, offset=8).astype(np.int32)

    return (
        read_images(d / "train-images-idx3-ubyte"),
        read_labels(d / "train-labels-idx1-ubyte"),
        read_images(d / "t10k-images-idx3-ubyte"),
        read_labels(d / "t10k-labels-idx1-ubyte"),
    )


def _load_condshift(cfg: Config) -> FederatedDataset:
    """Conditional-shift benchmark: client-dependent class conditionals where
    layer-selective personalization (MyAvg) should beat plain FedAvg.

    Clients belong to ``condshift_clusters`` clusters (``cfg.extra``,
    default 2).  All clusters share the SAME feature prototypes (a shared
    body can learn the prototype subspace from everyone's data), but each
    cluster maps prototypes to labels through its own permutation — the
    class-conditional p(x|y) differs per cluster while p(x) matches.  A
    single global head therefore averages contradictory label mappings
    (FedAvg caps near 1/clusters of its potential), while a personal head
    trained with same-cluster partners resolves its cluster's mapping.
    Per-client test shards (``test_client_idx``) follow each client's own
    cluster conditional — the quantity personalization optimizes.

    Fork-research counterpart: the MyAvg paper's motivating setting
    (``my_research/.../MyAvgAPI_7.py`` personalizes heads because clients'
    label semantics differ); this generator makes that setting measurable.
    """
    rng = np.random.RandomState(0xC04D ^ (cfg.random_seed * 2654435761 % (2**31)))
    d, classes = 64, 6
    n_clients = cfg.client_num_in_total
    clusters = int(cfg_extra(cfg, "condshift_clusters"))
    if not 1 <= clusters <= 6:
        # np.roll wraps at classes=6: more clusters would silently alias
        # earlier label permutations and measure LESS shift than configured
        raise ValueError(
            f"condshift_clusters={clusters} out of range [1, 6] "
            "(label permutations alias beyond the class count)"
        )
    per_client = int((cfg.synthetic_train_size or 4800) // max(n_clients, 1))
    test_per_client = int((cfg.synthetic_test_size or 1200) // max(n_clients, 1))
    scale = float(cfg_extra(cfg, "condshift_scale"))

    # shared prototype directions (unit-ish), one per class
    protos = rng.normal(0, 1.0, size=(classes, d)).astype(np.float32)
    protos /= np.linalg.norm(protos, axis=1, keepdims=True)
    # cluster c maps prototype p -> label perms[c][p]; cluster 0 = identity,
    # the rest are rotations (derangements) of the label set
    perms = [np.roll(np.arange(classes), c) for c in range(clusters)]

    def gen(cluster: int, n: int):
        p = rng.randint(0, classes, size=n)
        x = scale * protos[p] + rng.normal(0, 1.0, size=(n, d)).astype(np.float32)
        y = perms[cluster][p].astype(np.int32)
        return x.astype(np.float32), y

    xs, ys, txs, tys = [], [], [], []
    client_idx, test_client_idx = [], []
    tr_off = te_off = 0
    for cid in range(n_clients):
        cluster = cid % clusters
        x, y = gen(cluster, per_client)
        tx, ty = gen(cluster, test_per_client)
        xs.append(x); ys.append(y); txs.append(tx); tys.append(ty)
        client_idx.append(np.arange(tr_off, tr_off + per_client))
        test_client_idx.append(np.arange(te_off, te_off + test_per_client))
        tr_off += per_client
        te_off += test_per_client
    return FederatedDataset(
        train_x=np.concatenate(xs), train_y=np.concatenate(ys),
        test_x=np.concatenate(txs), test_y=np.concatenate(tys),
        client_idx=client_idx, test_client_idx=test_client_idx,
        class_num=classes, name="synthetic_condshift",
    )


def _synthetic_classification(name, feat, classes, n_train, n_test, seed):
    """Deterministic class-structured gaussians: per-class mean templates with
    additive noise — learnable by the real models (accuracy rises above the
    1/classes floor within a few rounds, which the smoke tests assert, matching
    the reference's 'tiny recipe, accuracy > floor' CI pattern, SURVEY §4)."""
    rng = np.random.RandomState(zlib.crc32(name.encode()) % (2**31) ^ seed)
    templates = rng.normal(0, 1.0, size=(classes,) + feat).astype(np.float32)

    def gen(n):
        y = rng.randint(0, classes, size=n).astype(np.int32)
        x = templates[y] + rng.normal(0, 1.2, size=(n,) + feat).astype(np.float32)
        return x.astype(np.float32), y

    train_x, train_y = gen(n_train)
    test_x, test_y = gen(n_test)
    return train_x, train_y, test_x, test_y


def _synthetic_hard(feat, classes, n_train, n_test, seed, modes_per_class: int = 4,
                    center_scale: float = 0.1):
    """Low-SNR synthetic benchmark (the per-class-gaussian stand-in saturates
    by round 9 and proves only wiring, not learning capability).

    Each class is a MIXTURE of ``modes_per_class`` gaussian clusters whose
    centers have per-coordinate scale ``center_scale`` against unit noise —
    an SNR of 0.1.  The cluster margin is ``center_scale * sqrt(d/2)`` ≈ 3.9
    sigma for CIFAR shapes, so the Bayes accuracy is ~100%, but ESTIMATING
    the 40 centers from data needs ~(sqrt(d)/margin)^2 ≈ 200 samples per
    cluster for a useful decision rule: accuracy is center-estimation-limited
    and grows smoothly with samples seen (measured: ~67% @ 8k train samples,
    ~75% @ 16k, 12 epochs — far from its ceiling, no early saturation).
    ``tests/test_accuracy_hard.py`` locks the expected-accuracy band per
    seed.  Deterministic in ``seed``.
    """
    rng = np.random.RandomState(0x5EED ^ (seed * 2654435761 % (2**31)))
    d = int(np.prod(feat))
    n_clusters = classes * modes_per_class
    if len(feat) == 3 and feat[0] % 4 == 0 and feat[1] % 4 == 0:
        # image shapes: LOW-FREQUENCY centers (low-res noise upsampled 4x) so
        # the class signal is spatially structured — convolutional models can
        # pool it out of the per-pixel noise, as with natural images (iid
        # per-pixel centers would make conv inductive bias useless)
        low = rng.normal(0, center_scale,
                         size=(n_clusters, feat[0] // 4, feat[1] // 4, feat[2]))
        centers = np.kron(low, np.ones((1, 4, 4, 1))).reshape(n_clusters, d).astype(np.float32)
    else:
        centers = rng.normal(0, center_scale, size=(n_clusters, d)).astype(np.float32)
    cluster_class = (np.arange(n_clusters) % classes).astype(np.int32)

    def gen(n):
        k = rng.randint(0, n_clusters, size=n)
        x = centers[k] + rng.normal(0, 1.0, size=(n, d)).astype(np.float32)
        return x.reshape((n,) + feat).astype(np.float32), cluster_class[k]

    train_x, train_y = gen(n_train)
    test_x, test_y = gen(n_test)
    return train_x, train_y, test_x, test_y


# ---------------------------------------------------------------------------
# text datasets (token sequences)
# ---------------------------------------------------------------------------

def _load_text_like(cfg: Config, name: str) -> FederatedDataset:
    seq_len, vocab = _TEXT_SPECS[name]
    cache = Path(os.path.expanduser(cfg.data_cache_dir))
    leaf = _try_load_leaf_text(name, cache, seq_len, vocab)
    if leaf is not None:
        train_x, train_y, test_x, test_y, client_idx = leaf
    else:
        if not cfg.synthetic_fallback:
            raise FileNotFoundError(f"{name} not found under {cache}")
        n_train = cfg.synthetic_train_size or 20000
        n_test = cfg.synthetic_test_size or 4000
        rng = np.random.RandomState(zlib.crc32(name.encode()) % (2**31) ^ cfg.random_seed)
        # Markov-chain token streams: next-token task is genuinely learnable.
        trans = rng.dirichlet(np.ones(vocab) * 0.05, size=vocab).astype(np.float64)

        def gen(n):
            seqs = np.empty((n, seq_len + 1), np.int32)
            state = rng.randint(0, vocab, size=n)
            seqs[:, 0] = state
            for t in range(1, seq_len + 1):
                u = rng.random(n)
                cdf = np.cumsum(trans[seqs[:, t - 1]], axis=1)
                seqs[:, t] = (u[:, None] > cdf).sum(axis=1)
            return seqs[:, :-1], seqs[:, 1:]

        train_x, train_y = gen(n_train)
        test_x, test_y = gen(n_test)
        client_idx = None
    if client_idx is None:
        labels = train_y[:, 0]  # partition by first target token
        client_idx = part.partition(
            cfg.partition_method, labels, cfg.client_num_in_total, cfg.partition_alpha, cfg.random_seed
        )
    return FederatedDataset(
        train_x=train_x, train_y=train_y, test_x=test_x, test_y=test_y,
        client_idx=client_idx, class_num=vocab, name=name,
    )


def _try_load_leaf_text(name: str, cache: Path, seq_len: int, vocab: int = 0):
    """LEAF json reader (``{"users": [...], "user_data": {user: {"x": ...,
    "y": ...}}}``).  Two encodings by task type:

    - char-level (shakespeare family, reference ``data/fed_shakespeare``):
      fixed character table, next-char targets;
    - word-level (reddit / stackoverflow_nwp, reference ``data/reddit``):
      whitespace tokens hash-bucketed into [1, vocab) (a fixed hashing
      vocabulary instead of the reference's shipped vocab file — zero-egress
      equivalent), next-word targets.  The char table CANNOT represent a 10k
      vocab, so word datasets must never take the char path.
    """
    d = cache / name
    train_file = next(iter(sorted((d / "train").glob("*.json"))), None) if d.is_dir() else None
    test_file = next(iter(sorted((d / "test").glob("*.json"))), None) if d.is_dir() else None
    if train_file is None or test_file is None:
        return None
    word_level = name in ("reddit", "stackoverflow_nwp")
    CHARS = sorted(set(
        "\n !\"&'(),-.0123456789:;>?ABCDEFGHIJKLMNOPQRSTUVWXYZ[]abcdefghijklmnopqrstuvwxyz}"
    ))
    table = {c: i + 1 for i, c in enumerate(CHARS)}

    def encode(s: str):
        arr = np.zeros(seq_len, np.int32)
        for i, c in enumerate(s[:seq_len]):
            arr[i] = table.get(c, 0)
        return arr

    def word_id(tok: str) -> int:
        return 1 + (zlib.crc32(tok.encode()) % (vocab - 1))

    def encode_words(tokens):
        arr = np.zeros(seq_len, np.int32)
        for i, t in enumerate(tokens[:seq_len]):
            arr[i] = word_id(t)
        return arr

    def _tokens(sample):
        # LEAF reddit x is a list of token lists (sentences) or a string
        if isinstance(sample, str):
            return sample.split()
        flat = []
        for part_ in sample:
            flat.extend(part_ if isinstance(part_, list) else str(part_).split())
        return flat

    def load_split(path):
        with open(path) as f:
            data = json.load(f)
        xs, ys, users = [], [], []
        for u in data["users"]:
            ud = data["user_data"][u]
            for sx, sy in zip(ud["x"], ud["y"]):
                if word_level:
                    tx = _tokens(sx)
                    ty = _tokens(sy) if sy else []
                    xs.append(encode_words(tx))
                    ys.append(encode_words(tx[1:] + ty[:1]))  # next-word shift
                else:
                    xs.append(encode(sx))
                    ys.append(encode(sx[1:] + sy))
                users.append(u)
        return np.stack(xs), np.stack(ys), users

    train_x, train_y, train_users = load_split(train_file)
    test_x, test_y, _ = load_split(test_file)
    uniq = sorted(set(train_users))
    umap = {u: i for i, u in enumerate(uniq)}
    client_idx = [[] for _ in uniq]
    for i, u in enumerate(train_users):
        client_idx[umap[u]].append(i)
    client_idx = [np.array(ix, np.int64) for ix in client_idx]
    return train_x, train_y, test_x, test_y, client_idx
