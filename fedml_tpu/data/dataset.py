"""Federated dataset containers.

The reference's loaders all return the 8-tuple
``[train_num, test_num, train_global, test_global, local_num_dict,
train_local_dict, test_local_dict, class_num]`` of torch DataLoaders
(``data/data_loader.py:234``).  Torch dataloaders are host-side iterators; a
TPU round wants **device-resident, statically-shaped** arrays.  So the native
container is :class:`FederatedDataset` (global arrays + per-client index
lists), and :func:`stack_clients` turns it into the padded
``(n_clients, capacity, ...)`` arrays + sample-count vector that the jitted
round consumes (SURVEY.md §7 hard part 1: ragged shards -> pad + mask).

``as_reference_tuple`` provides the 8-tuple shape (with numpy batch iterators
standing in for DataLoaders) for API-parity consumers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator, Optional

import numpy as np


@dataclass
class FederatedDataset:
    train_x: np.ndarray  # (N_train, ...) float32 features
    train_y: np.ndarray  # (N_train,) int labels (or multi-hot for *_lr tasks)
    test_x: np.ndarray
    test_y: np.ndarray
    client_idx: list  # list[np.ndarray] — per-client train sample indices
    class_num: int
    test_client_idx: Optional[list] = None  # per-client test split (LEAF-style)
    name: str = ""
    # segmentation datasets (FeTS2021): per-sample integer masks; train_y
    # then holds the dominant class for partitioning/eval-by-class
    masks: Optional[np.ndarray] = None
    test_masks: Optional[np.ndarray] = None

    @property
    def n_clients(self) -> int:
        return len(self.client_idx)

    @property
    def train_num(self) -> int:
        return int(self.train_x.shape[0])

    @property
    def test_num(self) -> int:
        return int(self.test_x.shape[0])

    def local_sample_counts(self) -> np.ndarray:
        return np.array([len(ix) for ix in self.client_idx], dtype=np.int32)


@dataclass
class StackedClientData:
    """Padded per-client arrays: the device-side form of the dataset.

    ``x``: (n_clients, capacity, *feat) — client shards padded to ``capacity``
    ``y``: (n_clients, capacity)
    ``counts``: (n_clients,) true sample counts (the FedAvg weights)
    Padding slots are cyclic repeats of real samples, so every slot is valid
    and ``fl.local_sgd`` draws batches from a per-epoch permutation of the FULL
    padded capacity (static shapes).  For a client with count < capacity this
    oversamples the cyclically-repeated low-index samples slightly relative to
    the reference's exact per-epoch shuffle over ``count``; aggregation weights
    use the true ``counts``, so the FedAvg weighting itself stays exact.
    """

    x: np.ndarray
    y: np.ndarray
    counts: np.ndarray

    @property
    def n_clients(self) -> int:
        return int(self.x.shape[0])

    @property
    def capacity(self) -> int:
        return int(self.x.shape[1])


def stack_clients(
    ds: FederatedDataset, capacity: Optional[int] = None, multiple_of: int = 1
) -> StackedClientData:
    """Pad client shards to a common capacity by cyclic repetition.

    Cyclic repetition (rather than zero-padding) keeps every slot a valid
    sample, so fixed-size batches can index ``(perm % count)`` without masks;
    weighting by true ``counts`` preserves the reference's sample-weighted
    FedAvg math exactly.

    ``multiple_of`` (typically the batch size) rounds the capacity up so the
    local-SGD scan's fixed-size batch slices always fit exactly.
    """
    counts = ds.local_sample_counts()
    cap = int(capacity if capacity is not None else counts.max())
    if multiple_of > 1:
        cap = ((cap + multiple_of - 1) // multiple_of) * multiple_of
    n = ds.n_clients
    x = np.empty((n, cap) + ds.train_x.shape[1:], dtype=ds.train_x.dtype)
    y = np.empty((n, cap) + ds.train_y.shape[1:], dtype=ds.train_y.dtype)
    for i, idxs in enumerate(ds.client_idx):
        if len(idxs) == 0:
            raise ValueError(f"client {i} has no samples")
        reps = np.resize(idxs, cap)  # cyclic repeat to capacity
        x[i] = ds.train_x[reps]
        y[i] = ds.train_y[reps]
    return StackedClientData(x=x, y=y, counts=counts)


def pad_eval_set(x: np.ndarray, y: np.ndarray, batch_size: int):
    """Tile an eval set up to a batch multiple (>= one full batch).

    Returns (x_padded, y_padded, n_valid); eval masks positions >= n_valid.
    np.resize-style tiling handles sets smaller than one batch.
    """
    n = x.shape[0]
    target = max(batch_size, ((n + batch_size - 1) // batch_size) * batch_size)
    if target != n:
        reps = np.resize(np.arange(n), target)
        x, y = x[reps], y[reps]
    return x, y, n


def batch_iterator(x: np.ndarray, y: np.ndarray, batch_size: int, seed: int = 0, shuffle: bool = True) -> Iterator:
    n = x.shape[0]
    order = np.random.RandomState(seed).permutation(n) if shuffle else np.arange(n)
    for s in range(0, n, batch_size):
        sel = order[s : s + batch_size]
        yield x[sel], y[sel]


def as_reference_tuple(ds: FederatedDataset, batch_size: int):
    """Reference 8-tuple shape (``data/data_loader.py:234``), numpy iterators
    in place of torch DataLoaders."""
    train_global = list(batch_iterator(ds.train_x, ds.train_y, batch_size, shuffle=False))
    test_global = list(batch_iterator(ds.test_x, ds.test_y, batch_size, shuffle=False))
    local_num = {i: len(ix) for i, ix in enumerate(ds.client_idx)}
    train_local = {
        i: list(batch_iterator(ds.train_x[ix], ds.train_y[ix], batch_size, shuffle=False))
        for i, ix in enumerate(ds.client_idx)
    }
    if ds.test_client_idx is not None:
        test_local = {
            i: list(batch_iterator(ds.test_x[ix], ds.test_y[ix], batch_size, shuffle=False))
            for i, ix in enumerate(ds.test_client_idx)
        }
    else:
        test_local = {i: test_global for i in range(ds.n_clients)}
    return [ds.train_num, ds.test_num, train_global, test_global, local_num, train_local, test_local, ds.class_num]
