"""Dataset breadth tail: ImageNet folders, UCI tables, NUS-WIDE, FeTS2021,
and the canonical edge-case poisoned sets.

Parity targets (each reader consumes the same on-disk layout the reference
expects, and every dataset keeps the deterministic synthetic fallback for
zero-egress environments):

- ImageNet / folder datasets  <- ``data/data_loader.py:375`` (ILSVRC2012 via
  ``load_partition_data_ImageNet``; class-per-directory layout)
- UCI SUSY + room occupancy   <- ``data/UCI/data_loader_for_susy_and_ro.py``
  (CSV streams: SUSY label-first CSV; occupancy detection txt tables)
- NUS-WIDE                    <- ``data/NUS_WIDE/nus_wide_dataset.py``
  (634 low-level features, top-k single-label selection; the pandas pipeline
  is reproduced when the raw layout is present, and a prepared ``.npz`` is
  the fast path)
- FeTS2021                    <- ``data/FeTS2021/download.sh`` (the reference
  ships only the fetch script; here prepared ``.npz`` volumes of
  (H, W, modalities) with integer tissue masks feed the FedSeg simulator)
- edge-case poisoned sets     <- ``data/edge_case_examples/data_loader.py``
  (Southwest-airline CIFAR pickles / ARDIS MNIST tensors consumed by the
  edge-case backdoor attack instead of synthesized tail samples)
"""

from __future__ import annotations

import csv
import logging
import os
import pickle
from pathlib import Path
from typing import Optional

import numpy as np

log = logging.getLogger("fedml_tpu.data.extra")


# ---------------------------------------------------------------------------
# ImageFolder (ImageNet layout: split/class_name/sample files)
# ---------------------------------------------------------------------------

def _read_image_file(p: Path) -> Optional[np.ndarray]:
    if p.suffix == ".npy":
        return np.load(p)
    if p.suffix.lower() in (".png", ".jpg", ".jpeg"):
        try:
            from PIL import Image
        except ImportError:
            log.warning("PIL not available; skipping %s (use .npy files)", p)
            return None
        return np.asarray(Image.open(p).convert("RGB"), dtype=np.float32) / 255.0
    return None


# in-RAM budget for folder datasets (~4 GB of float32): this reader
# materializes arrays (the TPU round wants static device arrays, not a
# host iterator), so full-size ILSVRC2012 (~770 GB) must be subset or
# pre-resized first — refuse loudly instead of OOMing
MAX_FOLDER_ELEMENTS = int(1e9)


def load_image_folder(root: Path, splits=("train", "val")):
    """Class-per-directory reader (torchvision ImageFolder layout, the shape
    ``load_partition_data_ImageNet`` consumes).  Classes are the sorted union
    of class-directory names across splits; every image must share one
    shape; every split must exist.  Returns (train_x, train_y, test_x,
    test_y, class_names)."""
    classes = sorted({
        d.name for split in splits if (root / split).is_dir()
        for d in (root / split).iterdir() if d.is_dir()
    })
    if not classes:
        raise FileNotFoundError(f"no class directories under {root}/{splits}")
    cls_id = {c: i for i, c in enumerate(classes)}
    out = {}
    for split in splits:
        xs, ys = [], []
        base = root / split
        if not base.is_dir():
            raise FileNotFoundError(
                f"split directory {base} is missing (a rank-1 empty split "
                "would crash eval downstream; unpack all splits)"
            )
        elements = 0
        for cdir in sorted(base.iterdir()):
            if not cdir.is_dir():
                continue
            for f in sorted(cdir.iterdir()):
                img = _read_image_file(f)
                if img is None:
                    continue
                elements += int(np.prod(img.shape))
                if elements > MAX_FOLDER_ELEMENTS:
                    raise MemoryError(
                        f"image folder {base} exceeds the in-RAM budget of "
                        f"{MAX_FOLDER_ELEMENTS} float32 elements; subsample "
                        "or pre-resize the dataset (full ILSVRC2012 does not "
                        "fit host RAM as dense arrays)"
                    )
                xs.append(np.asarray(img, np.float32))
                ys.append(cls_id[cdir.name])
        if not xs:
            raise FileNotFoundError(f"no readable images under {base}")
        shapes = {x.shape for x in xs}
        if len(shapes) != 1:
            raise ValueError(f"inconsistent image shapes under {base}: {shapes}")
        out[split] = (np.stack(xs), np.asarray(ys, np.int32))
    return out[splits[0]] + out[splits[1]] + (classes,)


# ---------------------------------------------------------------------------
# UCI tables
# ---------------------------------------------------------------------------

def load_susy(d: Path, test_frac: float = 0.2):
    """SUSY.csv: label first, 18 features (``data_loader_for_susy_and_ro.py``
    reads the same CSV stream).  Deterministic tail split for test."""
    path = d / "SUSY.csv"
    x, y = [], []
    with open(path) as f:
        for row in csv.reader(f):
            if not row:
                continue
            y.append(int(float(row[0])))
            x.append([float(v) for v in row[1:19]])
    x = np.asarray(x, np.float32)
    y = np.asarray(y, np.int32)
    n_test = max(1, int(len(x) * test_frac))
    return x[:-n_test], y[:-n_test], x[-n_test:], y[-n_test:]


def load_room_occupancy(d: Path):
    """UCI occupancy detection: datatraining.txt / datatest.txt with columns
    id,date,Temperature,Humidity,Light,CO2,HumidityRatio,Occupancy."""
    def read(p: Path):
        xs, ys = [], []
        with open(p) as f:
            reader = csv.reader(f)
            header = next(reader)
            # feature columns = the 5 numeric sensor channels
            for row in reader:
                if len(row) < 7:
                    continue
                xs.append([float(v) for v in row[-6:-1]])
                ys.append(int(float(row[-1])))
        return np.asarray(xs, np.float32), np.asarray(ys, np.int32)

    tr = read(d / "datatraining.txt")
    te = read(d / "datatest.txt")
    return tr[0], tr[1], te[0], te[1]


# ---------------------------------------------------------------------------
# NUS-WIDE
# ---------------------------------------------------------------------------

def load_nus_wide(d: Path, top_k: int = 5):
    """Prepared fast path: ``nus_wide_prepared.npz`` with train_x/train_y/
    test_x/test_y (634-dim low-level features, single top-k label ids).
    When only the raw NUS-WIDE layout exists and pandas is importable, the
    reference pipeline (``nus_wide_dataset.py:get_labeled_data...``: top-k
    labels by count, rows with exactly one active label, normalized
    low-level feature concat) prepares the npz once."""
    npz = d / "nus_wide_prepared.npz"
    if npz.exists():
        z = np.load(npz)
        return (z["train_x"].astype(np.float32), z["train_y"].astype(np.int32),
                z["test_x"].astype(np.float32), z["test_y"].astype(np.int32))
    arrays = _prepare_nus_wide(d, top_k)
    np.savez(npz, train_x=arrays[0], train_y=arrays[1], test_x=arrays[2], test_y=arrays[3])
    return arrays


def _prepare_nus_wide(d: Path, top_k: int):
    try:
        import pandas as pd
    except ImportError as e:
        raise FileNotFoundError(
            f"{d}/nus_wide_prepared.npz absent and pandas unavailable to "
            "prepare it from the raw NUS-WIDE layout"
        ) from e
    labels_dir = d / "Groundtruth" / "AllLabels"
    counts = {}
    for f in sorted(labels_dir.iterdir()):
        label = f.stem.split("_")[-1]
        col = pd.read_csv(f, header=None)[0]
        counts[label] = int((col == 1).sum())
    selected = [k for k, _ in sorted(counts.items(), key=lambda kv: kv[1], reverse=True)[:top_k]]

    out = []
    for split in ("Train", "Test"):
        dfs = []
        for label in selected:
            f = d / "Groundtruth" / "TrainTestLabels" / f"Labels_{label}_{split}.txt"
            dfs.append(pd.read_csv(f, header=None).rename(columns={0: label}))
        lab = pd.concat(dfs, axis=1)
        mask = lab.sum(axis=1) == 1 if top_k > 1 else lab[selected[0]] == 1
        feats = []
        for f in sorted((d / "Low_Level_Features").iterdir()):
            if f.name.startswith(f"{split}_Normalized"):
                df = pd.read_csv(f, header=None, sep=" ").dropna(axis=1)
                feats.append(df)
        x = pd.concat(feats, axis=1).loc[mask[mask].index].to_numpy(np.float32)
        y = lab.loc[mask[mask].index, selected].to_numpy().argmax(axis=1).astype(np.int32)
        out.extend([x, y])
    return tuple(out)


# ---------------------------------------------------------------------------
# FeTS2021 (federated tumor segmentation)
# ---------------------------------------------------------------------------

def load_fets2021(d: Path):
    """Prepared volumes: ``fets2021_prepared.npz`` holding train_x/test_x
    (N, H, W, modalities) float32 and train_m/test_m (N, H, W) int32 tissue
    masks (the reference ships only a download script; volume preparation is
    the operator's step, as there).  Returns (x, masks, tx, tmasks)."""
    z = np.load(d / "fets2021_prepared.npz")
    return (z["train_x"].astype(np.float32), z["train_m"].astype(np.int32),
            z["test_x"].astype(np.float32), z["test_m"].astype(np.int32))


def synthesize_fets_like(n_train: int, n_test: int, seed: int, hw: int = 64,
                         modalities: int = 4, classes: int = 4):
    """Deterministic FeTS-shaped stand-in: smooth 'anatomy' + a blob tumor
    region per class painted into the mask."""
    rng = np.random.RandomState(0xFE75 ^ seed)

    def gen(n):
        base = rng.normal(0, 1, (n, hw, hw, modalities)).astype(np.float32)
        masks = np.zeros((n, hw, hw), np.int32)
        for i in range(n):
            c = rng.randint(1, classes)
            cx, cy = rng.randint(hw // 4, 3 * hw // 4, size=2)
            r = rng.randint(hw // 10, hw // 5)
            yy, xx = np.mgrid[:hw, :hw]
            blob = (yy - cy) ** 2 + (xx - cx) ** 2 <= r * r
            masks[i][blob] = c
            base[i][blob] += 2.0 * c / classes  # lesion intensity signal
        return base, masks

    x, m = gen(n_train)
    tx, tm = gen(n_test)
    return x, m, tx, tm


# ---------------------------------------------------------------------------
# edge-case poisoned sets (Wang et al. NeurIPS'20)
# ---------------------------------------------------------------------------

def load_edge_case_sets(cache: Path, poison_type: str = "southwest"):
    """The canonical poisoned example sets the reference downloads
    (``edge_case_examples/data_loader.py:460``): Southwest-airplane CIFAR
    pickles or ARDIS MNIST tensors.  Returns (train_examples, test_examples)
    as float arrays, or None when the files are absent."""
    d = cache / "edge_case_examples"
    try:
        if poison_type == "southwest":
            with open(d / "southwest_cifar10" / "southwest_images_new_train.pkl", "rb") as f:
                train = pickle.load(f)
            with open(d / "southwest_cifar10" / "southwest_images_new_test.pkl", "rb") as f:
                test = pickle.load(f)
            train = np.asarray(train, np.float32)
            test = np.asarray(test, np.float32)
            if train.max() > 1.5:  # uint8 pickles
                train, test = train / 255.0, test / 255.0
            return train, test
        if poison_type == "ardis":
            import torch  # cpu torch is in the image

            ds = torch.load(d / "ARDIS" / "ardis_test_dataset.pt")
            imgs = np.asarray([np.asarray(s[0]) for s in ds], np.float32)
            if imgs.ndim == 3:
                imgs = imgs[..., None]
            n = len(imgs) // 2
            return imgs[:n], imgs[n:]
    except FileNotFoundError:
        return None
    except Exception:  # corrupt archive: treat as absent, synthesize instead
        log.exception("failed to read edge-case set %r under %s", poison_type, d)
        return None
    return None
