"""Client data partitioners.

Semantics-parity with the reference partitioners
(``data/cifar10/data_loader.py:122-162`` ``partition_data`` and
``core/data/noniid_partition.py``):

- ``homo``      — IID: a random permutation split into equal shards.
- ``hetero``    — non-IID: per-class Dirichlet(alpha) proportions with the
                  reference's min-size-10 rebalancing loop (resample until the
                  smallest client shard has >= 10 samples).
- ``hetero-fix``— fixed distribution from a provided table.

Pure functions of ``(labels, n_clients, alpha, seed)`` — no global numpy state
— so partitions are reproducible across backends and hosts.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

MIN_PARTITION_SIZE = 10  # reference: `while min_size < 10` rebalancing loop


def partition_homo(n_samples: int, n_clients: int, seed: int = 0) -> list[np.ndarray]:
    rng = np.random.RandomState(seed)
    idxs = rng.permutation(n_samples)
    return [np.sort(part) for part in np.array_split(idxs, n_clients)]


def partition_hetero_dirichlet(
    labels: np.ndarray, n_clients: int, alpha: float, seed: int = 0
) -> list[np.ndarray]:
    """Per-class Dirichlet(alpha) partition with min-size rebalance.

    Mirrors the reference loop (``data/cifar10/data_loader.py:136-162``):
    for each class, draw Dirichlet proportions over clients, down-weight
    clients already holding >= N/n samples, split that class's indices by the
    cumulative proportions; repeat the whole draw until min client size >= 10.
    """
    rng = np.random.RandomState(seed)
    n = labels.shape[0]
    classes = np.unique(labels)
    min_size = 0
    idx_batch: list[list[int]] = [[] for _ in range(n_clients)]
    guard = 0
    while min_size < MIN_PARTITION_SIZE:
        guard += 1
        if guard > 1000:
            raise RuntimeError("dirichlet partition failed to reach min size; alpha too small for dataset")
        idx_batch = [[] for _ in range(n_clients)]
        for k in classes:
            idx_k = np.where(labels == k)[0]
            rng.shuffle(idx_k)
            proportions = rng.dirichlet(np.repeat(alpha, n_clients))
            # balance clause from the reference: zero out clients already full
            proportions = np.array(
                [p * (len(idx_j) < n / n_clients) for p, idx_j in zip(proportions, idx_batch)]
            )
            proportions = proportions / proportions.sum()
            split_points = (np.cumsum(proportions) * len(idx_k)).astype(int)[:-1]
            for j, part in enumerate(np.split(idx_k, split_points)):
                idx_batch[j].extend(part.tolist())
        min_size = min(len(b) for b in idx_batch)
    return [np.sort(np.array(b, dtype=np.int64)) for b in idx_batch]


def partition_hetero_fix(
    labels: np.ndarray, n_clients: int, distribution: Sequence[Sequence[float]]
) -> list[np.ndarray]:
    """Fixed per-client class distribution table (reference ``hetero-fix``:
    reads a distribution file; here the table is passed in directly)."""
    dist = np.asarray(distribution, dtype=np.float64)  # (n_clients, n_classes)
    classes = np.unique(labels)
    out: list[list[int]] = [[] for _ in range(n_clients)]
    for ci, k in enumerate(classes):
        idx_k = np.where(labels == k)[0]
        props = dist[:, ci] / max(dist[:, ci].sum(), 1e-12)
        split_points = (np.cumsum(props) * len(idx_k)).astype(int)[:-1]
        for j, part in enumerate(np.split(idx_k, split_points)):
            out[j].extend(part.tolist())
    return [np.sort(np.array(b, dtype=np.int64)) for b in out]


def partition(
    method: str,
    labels: np.ndarray,
    n_clients: int,
    alpha: float = 0.5,
    seed: int = 0,
    distribution: Optional[Sequence[Sequence[float]]] = None,
) -> list[np.ndarray]:
    if method == "homo":
        return partition_homo(labels.shape[0], n_clients, seed)
    if method == "hetero":
        return partition_hetero_dirichlet(labels, n_clients, alpha, seed)
    if method == "hetero-fix":
        if distribution is None:
            raise ValueError("hetero-fix requires a distribution table")
        return partition_hetero_fix(labels, n_clients, distribution)
    raise ValueError(f"unknown partition method {method!r}")


def record_data_stats(labels: np.ndarray, idx_map: list[np.ndarray]) -> dict:
    """Per-client class histogram (reference ``record_net_data_stats``)."""
    stats = {}
    for i, idxs in enumerate(idx_map):
        unq, cnt = np.unique(labels[idxs], return_counts=True)
        stats[i] = {int(u): int(c) for u, c in zip(unq, cnt)}
    return stats
