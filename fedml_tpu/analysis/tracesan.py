"""Runtime trace sanitizer — the dynamic half of the GL010/GL011 contract.

The static rules flag *syntactic* host-sync and recompile hazards; they
cannot prove the steady-state round loop is actually clean, nor catch a
transfer smuggled through a code path the reachability walk missed.  This
module turns jax's own instrumentation into a gate:

- :func:`round_guard` scopes ``jax.transfer_guard("disallow")`` around a
  steady-state round (rounds past the warmup count, default 1), so any
  IMPLICIT device<->host transfer inside the round body raises instead of
  silently serializing the pipeline.  Explicit syncs (``jax.device_get``)
  stay legal — the contract is "every host boundary is deliberate", not
  "no host boundaries".
- :func:`allow` re-opens the guard for an annotated legitimate boundary
  (wire encode, checkpoint save, streamed fold ingest, round-boundary
  metric export) and counts each crossing per site, so the report shows
  exactly where the round loop touches the host and how often.
- a ``jax.monitoring`` listener counts every real XLA backend compile and
  attributes it to the first ``fedml_tpu`` frame on the calling stack;
  compiles witnessed INSIDE a steady-state guard are recompile hazards
  (the GL011 failure mode, observed rather than inferred).

Gating is absolute: unless ``FEDML_TPU_TRACESAN=1`` is set,
:func:`maybe_install_from_env` does nothing, :func:`round_guard` /
:func:`allow` return null context managers, and jax is never imported
from here — zero overhead, zero behavior change (the tier-1 suite pins
the default path bitwise).  When enabled, a JSON report dumps at
interpreter exit to ``FEDML_TPU_TRACESAN_REPORT`` or a summary to
stderr, and the tracesan gate in ``tests/test_tracesan.py`` fails if a
steady-state round ever witnesses a disallowed transfer or a compile.

Counter families (registered at import, like every obs module):
``fedml_tracesan_guarded_rounds_total``,
``fedml_tracesan_allowed_transfers_total{site}``,
``fedml_tracesan_compiles_total{phase}``,
``fedml_tracesan_violations_total{kind}``.
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import sys
import threading
import traceback

from ..obs.registry import REGISTRY

ENV_FLAG = "FEDML_TPU_TRACESAN"
ENV_REPORT = "FEDML_TPU_TRACESAN_REPORT"
ENV_WARMUP = "FEDML_TPU_TRACESAN_WARMUP"

#: the jax.monitoring event key a real XLA backend compile emits (tracing a
#: cache-hit program does NOT fire it — exactly the recompile signal we want)
_COMPILE_KEY = "/jax/core/compile/backend_compile_duration"

#: bound on stored per-event records so a pathological run cannot grow the
#: report without bound (mirrors sanitizer._MAX_LONG_HOLDS)
_MAX_EVENTS = 200

GUARDED_ROUNDS = REGISTRY.counter(
    "fedml_tracesan_guarded_rounds_total",
    "steady-state rounds executed under jax.transfer_guard('disallow')")
ALLOWED_TRANSFERS = REGISTRY.counter(
    "fedml_tracesan_allowed_transfers_total",
    "annotated host-boundary crossings while the sanitizer is active",
    labels=("site",))
COMPILES = REGISTRY.counter(
    "fedml_tracesan_compiles_total",
    "XLA backend compiles witnessed, by round phase",
    labels=("phase",))
VIOLATIONS = REGISTRY.counter(
    "fedml_tracesan_violations_total",
    "trace-hygiene violations: disallowed transfers / steady-state compiles",
    labels=("kind",))

_ACTIVE: "TraceSanitizer | None" = None
#: jax.monitoring has no unregister API — register the dispatching listener
#: once per process and route through whatever sanitizer is active
_LISTENER_INSTALLED = False


def _attribute_site(limit: int = 8) -> tuple[str, list[str]]:
    """('pkg/module.py:123:fn', short stack) of the innermost ``fedml_tpu``
    frame below this module — where package code triggered the event."""
    frames = traceback.extract_stack()[:-2]
    site = "<outside-package>"
    for frame in reversed(frames):
        path = frame.filename.replace("\\", "/")
        if "fedml_tpu/" in path and "analysis/tracesan" not in path:
            parts = path.split("/")
            site = f"{'/'.join(parts[-2:])}:{frame.lineno}:{frame.name}"
            break
    out = []
    for frame in frames[-limit:]:
        parts = frame.filename.replace("\\", "/").split("/")
        out.append(f"{'/'.join(parts[-2:])}:{frame.lineno}:{frame.name}")
    return site, out


class TraceSanitizer:
    """Shared state behind the process's transfer/compile guard."""

    def __init__(self, warmup_rounds: int = 1):
        self.warmup_rounds = int(warmup_rounds)
        self._mu = threading.Lock()
        #: guard phase is per-thread: the compile listener fires on the
        #: thread running the dispatch, so attribution follows the caller
        self._tls = threading.local()
        self.guarded_rounds = 0
        self.allowed_sites: dict[str, int] = {}
        self.compiles: dict[str, int] = {}      # phase -> count
        self.compile_events: list[dict] = []
        self.violations: list[dict] = []

    # -- per-thread phase ------------------------------------------------------
    def _phase(self) -> str:
        if getattr(self._tls, "allowed", 0):
            # inside an annotated host boundary: exempt from the steady-
            # compile hazard the same way it is from the transfer guard
            return "allowed"
        if getattr(self._tls, "steady", 0):
            return "steady"
        if getattr(self._tls, "warmup", 0):
            return "warmup"
        return "unguarded"

    def _round(self) -> "int | None":
        return getattr(self._tls, "round_idx", None)

    # -- context managers ------------------------------------------------------
    @contextlib.contextmanager
    def round_guard(self, round_idx: int, rounds: int = 1):
        import jax

        steady = round_idx >= self.warmup_rounds
        attr = "steady" if steady else "warmup"
        prev_round = getattr(self._tls, "round_idx", None)
        setattr(self._tls, attr, getattr(self._tls, attr, 0) + 1)
        self._tls.round_idx = round_idx
        if steady:
            with self._mu:
                self.guarded_rounds += rounds
            GUARDED_ROUNDS.inc(rounds)
        try:
            if steady:
                with jax.transfer_guard("disallow"):
                    yield
            else:
                yield
        except jax.errors.JaxRuntimeError as e:
            # the transfer guard raises from inside the traced/dispatched
            # computation; record the witness before the gate re-raises
            if "transfer" in str(e).lower():
                site, stack = _attribute_site()
                VIOLATIONS.inc(kind="disallowed_transfer")
                with self._mu:
                    if len(self.violations) < _MAX_EVENTS:
                        self.violations.append({
                            "kind": "disallowed_transfer", "round": round_idx,
                            "site": site, "error": str(e).split("\n")[0],
                            "stack": stack,
                        })
            raise
        finally:
            setattr(self._tls, attr, getattr(self._tls, attr, 1) - 1)
            self._tls.round_idx = prev_round

    @contextlib.contextmanager
    def allow(self, site: str):
        import jax

        with self._mu:
            self.allowed_sites[site] = self.allowed_sites.get(site, 0) + 1
        ALLOWED_TRANSFERS.inc(site=site)
        self._tls.allowed = getattr(self._tls, "allowed", 0) + 1
        try:
            with jax.transfer_guard("allow"):
                yield
        finally:
            self._tls.allowed -= 1

    # -- compile listener ------------------------------------------------------
    def on_compile(self, duration_s: float) -> None:
        phase = self._phase()
        site, stack = _attribute_site()
        COMPILES.inc(phase=phase)
        record = {"phase": phase, "round": self._round(), "site": site,
                  "duration_s": round(float(duration_s), 4), "stack": stack}
        with self._mu:
            self.compiles[phase] = self.compiles.get(phase, 0) + 1
            if len(self.compile_events) < _MAX_EVENTS:
                self.compile_events.append(record)
            if phase == "steady" and len(self.violations) < _MAX_EVENTS:
                self.violations.append(dict(record, kind="steady_compile"))
        if phase == "steady":
            VIOLATIONS.inc(kind="steady_compile")

    # -- reporting -------------------------------------------------------------
    def report(self) -> dict:
        with self._mu:
            return {
                "warmup_rounds": self.warmup_rounds,
                "guarded_rounds": self.guarded_rounds,
                "allowed_sites": dict(sorted(self.allowed_sites.items())),
                "compiles": dict(sorted(self.compiles.items())),
                "compile_events": list(self.compile_events),
                "violations": list(self.violations),
            }


def _dispatch_compile_event(key: str, duration_s: float, **kw) -> None:
    san = _ACTIVE
    if san is not None and key == _COMPILE_KEY:
        san.on_compile(duration_s)


def install(warmup_rounds: int | None = None) -> TraceSanitizer:
    """Activate the sanitizer (imports jax; registers the process-wide
    compile listener on first call).  Idempotent."""
    global _ACTIVE, _LISTENER_INSTALLED
    if _ACTIVE is not None:
        return _ACTIVE
    if warmup_rounds is None:
        warmup_rounds = int(os.environ.get(ENV_WARMUP, "1"))
    if not _LISTENER_INSTALLED:
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(_dispatch_compile_event)
        _LISTENER_INSTALLED = True
    _ACTIVE = TraceSanitizer(warmup_rounds=warmup_rounds)
    atexit.register(_dump_on_exit)
    return _ACTIVE


def uninstall() -> None:
    """Deactivate (the monitoring listener stays registered — jax has no
    unregister API — but dispatches to nothing)."""
    global _ACTIVE
    _ACTIVE = None


def active() -> "TraceSanitizer | None":
    return _ACTIVE


def maybe_install_from_env() -> "TraceSanitizer | None":
    """The one public entry point for harness code: a strict no-op unless
    ``FEDML_TPU_TRACESAN=1``."""
    if os.environ.get(ENV_FLAG) == "1":
        return install()
    return None


def round_guard(round_idx: int, rounds: int = 1):
    """Guard one round of the hot loop.  Null context when inactive; a
    warmup round (``round_idx < warmup_rounds``) tracks phase only; a
    steady round runs under ``jax.transfer_guard("disallow")``."""
    san = _ACTIVE
    if san is None:
        return contextlib.nullcontext()
    return san.round_guard(round_idx, rounds)


def allow(site: str):
    """Annotate a legitimate host boundary.  Null context when inactive;
    active, it re-opens the transfer guard and counts the crossing."""
    san = _ACTIVE
    if san is None:
        return contextlib.nullcontext()
    return san.allow(site)


def _dump_on_exit() -> None:
    san = _ACTIVE
    if san is None:
        return
    rep = san.report()
    path = os.environ.get(ENV_REPORT)
    if path:
        try:
            with open(path, "w") as f:
                json.dump(rep, f, indent=1)
                f.write("\n")
        except OSError:
            path = None
    if not path:
        summary = {k: rep[k] for k in ("guarded_rounds", "allowed_sites", "compiles")}
        summary["violations"] = len(rep["violations"])
        print(f"FEDML_TPU_TRACESAN report: {json.dumps(summary)}", file=sys.stderr)
        for v in rep["violations"]:
            print(f"TRACESAN VIOLATION: {v['kind']} at {v['site']} "
                  f"(round {v['round']})", file=sys.stderr)
