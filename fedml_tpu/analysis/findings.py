"""Findings model: the result record, inline suppressions, and the baseline.

A :class:`Finding` is one rule violation at one site.  Its :attr:`Finding.key`
is deliberately line-independent (``rule:path:symbol``) so baseline entries
survive unrelated edits above the finding; only when a rule has no natural
symbol does the line number anchor the key.

Suppressions are pylint-style comments::

    x = extra.get("weird")  # graftlint: disable=GL001(migrating in PR 12)
    def caller_holds_lock(self):  # graftlint: disable=GL004(single caller owns _agg_lock)

A suppression on a ``def``/``class`` line covers that whole body; anywhere
else it covers its own line.  The reason in parentheses is required reading
for reviewers, not parsed.

The baseline (``analysis/baseline.json``) is the escape hatch for
pre-existing findings a PR cannot fix; this repo ships it EMPTY — the
tier-1 gate means every new finding is either fixed or suppressed inline
with a reason, never silently baselined.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable

SEVERITIES = ("error", "warning")

_DISABLE_RE = re.compile(r"graftlint:\s*disable=([A-Za-z0-9_,()\s][^#]*)")
_RULE_ID_RE = re.compile(r"(GL\d{3})(?:\(([^)]*)\))?")


@dataclass
class Finding:
    rule: str          # "GL001"
    path: str          # package-relative posix path, e.g. "cross_silo/server.py"
    line: int
    message: str
    severity: str = "error"
    symbol: str = ""   # stable anchor (flag name, attribute, metric family)

    @property
    def key(self) -> str:
        anchor = self.symbol if self.symbol else f"L{self.line}"
        return f"{self.rule}:{self.path}:{anchor}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} [{self.severity}] {self.message}"


def parse_suppressions(source: str) -> dict[int, set[str]]:
    """``{lineno: {rule ids disabled on that line}}`` from graftlint comments.

    Works on raw source lines (not tokenize) so even syntactically bold
    fixture snippets parse; a ``#`` inside a string literal that happens to
    spell a directive would over-suppress, which is harmless and unheard of.
    """
    out: dict[int, set[str]] = {}
    for lineno, text in enumerate(source.splitlines(), start=1):
        if "graftlint" not in text:
            continue
        m = _DISABLE_RE.search(text)
        if not m:
            continue
        ids = {rid for rid, _reason in _RULE_ID_RE.findall(m.group(1))}
        if ids:
            out.setdefault(lineno, set()).update(ids)
    return out


# -- baseline ----------------------------------------------------------------

BASELINE_VERSION = 1


def load_baseline(path: str | Path) -> set[str]:
    """The set of finding keys grandfathered by the checked-in baseline."""
    p = Path(path)
    if not p.exists():
        return set()
    doc = json.loads(p.read_text())
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(f"unsupported baseline version in {p}: {doc.get('version')!r}")
    return {entry["key"] for entry in doc.get("findings", [])}


def save_baseline(path: str | Path, findings: Iterable[Finding]) -> None:
    doc = {
        "version": BASELINE_VERSION,
        "findings": [
            {"key": f.key, "rule": f.rule, "path": f.path, "line": f.line,
             "message": f.message}
            for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
        ],
    }
    Path(path).write_text(json.dumps(doc, indent=1) + "\n")
