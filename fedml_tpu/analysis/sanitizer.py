"""Runtime lock sanitizer — the dynamic half of the GL007 contract.

The static lock-order rule sees ``self``-method call chains; it cannot see
a manager-lock -> ledger-lock -> registry-lock chain crossing three
objects, nor tell which of two theoretically-inverted orders a real run
actually exercises.  This module instruments ``threading.Lock``/``RLock``
*construction* so every lock created from ``fedml_tpu`` code records, at
test time:

- the **per-thread lock-order graph**: an edge ``A -> B`` whenever a
  thread acquires ``B`` while holding ``A`` (per lock *instance*, with the
  creation site as the human-readable label);
- **hold times** per creation site, plus every hold longer than
  ``FEDML_TPU_LOCKSAN_HOLD_S`` (default 0.5s) as a long-hold outlier with
  the holder's stack;
- **inversions**: cycles in the instance-order graph — the witnessed
  two-sided evidence (``A`` before ``B`` on one thread, ``B`` before ``A``
  on another) that a deadlock interleaving exists.

Gating is absolute: unless ``FEDML_TPU_LOCKSAN=1`` is set,
:func:`maybe_install_from_env` does nothing and ``threading.Lock`` is
untouched — zero overhead, zero behavior change.  When enabled (the
conftest installs it before any fedml_tpu module is imported, so
module-level and constructor locks all route through the factory), a
report dumps at interpreter exit to ``FEDML_TPU_LOCKSAN_REPORT`` (JSON)
or stderr, and ``tests/test_sanitizer.py`` fails tier-1 if the async/comm
suite ever witnesses an inversion.

Locks created by foreign code (stdlib ``queue``, jax, ``threading.Event``
internals) are left uninstrumented on purpose: the contract covers the
package's ~34 lock sites, and instrumenting the interpreter's own plumbing
would measure the sanitizer, not the framework.
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time
import traceback

ENV_FLAG = "FEDML_TPU_LOCKSAN"
ENV_REPORT = "FEDML_TPU_LOCKSAN_REPORT"
ENV_HOLD = "FEDML_TPU_LOCKSAN_HOLD_S"

#: bound on stored long-hold records / example stacks so a pathological run
#: cannot grow the report without bound
_MAX_LONG_HOLDS = 200

# the REAL factories, captured at import: the sanitizer's own bookkeeping
# lock must never be an instrumented lock
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

_ACTIVE: "LockSanitizer | None" = None


def _creation_site(depth: int = 2) -> tuple[str, str]:
    """(full path, 'pkg/module.py:123' label) of the frame that called
    ``threading.Lock()``, skipping sanitizer/threading internals (so an
    ``Event`` created by package code attributes to the package line)."""
    f = sys._getframe(depth)
    while f is not None:
        path = f.f_code.co_filename.replace("\\", "/")
        if "sanitizer" not in path and not path.endswith("threading.py"):
            parts = path.split("/")
            return path, "/".join(parts[-2:]) + f":{f.f_lineno}"
        f = f.f_back
    return "<unknown>", "<unknown>"


def _short_stack(limit: int = 6) -> list[str]:
    out = []
    for frame in traceback.extract_stack()[:-2][-limit:]:
        parts = frame.filename.replace("\\", "/").split("/")
        out.append(f"{'/'.join(parts[-2:])}:{frame.lineno}:{frame.name}")
    return out


class _Held:
    __slots__ = ("serial", "site", "t0", "depth")

    def __init__(self, serial: int, site: str, t0: float):
        self.serial = serial
        self.site = site
        self.t0 = t0
        self.depth = 1


class LockSanitizer:
    """Shared state behind every instrumented lock in the process."""

    def __init__(self, long_hold_s: float = 0.5):
        self.long_hold_s = float(long_hold_s)
        self._mu = _REAL_LOCK()
        self._serial = 0
        #: (serial_a, serial_b) -> count; site labels ride _sites
        self.edges: dict[tuple[int, int], int] = {}
        self._sites: dict[int, str] = {}
        #: first example per edge: (thread name, short stack)
        self._edge_examples: dict[tuple[int, int], tuple[str, list[str]]] = {}
        #: site -> [holds, total_s, max_s]
        self.holds: dict[str, list] = {}
        self.long_holds: list[dict] = []
        self._tls = threading.local()

    # -- registration ---------------------------------------------------------
    def register(self, site: str) -> int:
        with self._mu:
            self._serial += 1
            self._sites[self._serial] = site
            return self._serial

    def _stack(self) -> list[_Held]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    # -- acquire/release hooks ------------------------------------------------
    def on_acquired(self, serial: int, site: str) -> None:
        stack = self._stack()
        for held in stack:
            if held.serial == serial:  # reentrant re-acquire: no new edge
                held.depth += 1
                return
        if stack:
            now_edges = [(h.serial, serial) for h in stack]
            tname = threading.current_thread().name
            with self._mu:
                for e in now_edges:
                    self.edges[e] = self.edges.get(e, 0) + 1
                    if e not in self._edge_examples \
                            and len(self._edge_examples) < 4 * _MAX_LONG_HOLDS:
                        self._edge_examples[e] = (tname, _short_stack())
        stack.append(_Held(serial, site, time.monotonic()))

    def on_released(self, serial: int) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            held = stack[i]
            if held.serial == serial:
                held.depth -= 1
                if held.depth > 0:
                    return
                del stack[i]
                dur = time.monotonic() - held.t0
                with self._mu:
                    agg = self.holds.setdefault(held.site, [0, 0.0, 0.0])
                    agg[0] += 1
                    agg[1] += dur
                    agg[2] = max(agg[2], dur)
                    if dur >= self.long_hold_s and len(self.long_holds) < _MAX_LONG_HOLDS:
                        self.long_holds.append({
                            "site": held.site, "held_s": round(dur, 4),
                            "thread": threading.current_thread().name,
                            "stack": _short_stack(),
                        })
                return
        # released on a thread that never recorded the acquire (e.g. a
        # Condition handoff): nothing to time — ignore

    def on_released_fully(self, serial: int) -> None:
        """Condition.wait released the lock through ``_release_save``:
        close the hold record regardless of reentrant depth."""
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i].serial == serial:
                stack[i].depth = 1
                self.on_released(serial)
                return

    # -- reporting ------------------------------------------------------------
    def _cycles(self, edges: set[tuple[int, int]]) -> list[list[int]]:
        """Strongly connected components of size>1 in the instance graph —
        each is a witnessed order inversion."""
        adj: dict[int, set[int]] = {}
        for a, b in edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        index: dict[int, int] = {}
        low: dict[int, int] = {}
        on_stack: set[int] = set()
        stack: list[int] = []
        counter = [0]
        comps: list[list[int]] = []
        for root in adj:
            if root in index:
                continue
            work = [(root, iter(sorted(adj[root])))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                node, it = work[-1]
                advanced = False
                for nxt in it:
                    if nxt not in index:
                        index[nxt] = low[nxt] = counter[0]
                        counter[0] += 1
                        stack.append(nxt)
                        on_stack.add(nxt)
                        work.append((nxt, iter(sorted(adj[nxt]))))
                        advanced = True
                        break
                    if nxt in on_stack:
                        low[node] = min(low[node], index[nxt])
                if advanced:
                    continue
                work.pop()
                if work:
                    low[work[-1][0]] = min(low[work[-1][0]], low[node])
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) > 1:
                        comps.append(sorted(comp))
        return comps

    def report(self) -> dict:
        with self._mu:
            edges = set(self.edges)
            sites = dict(self._sites)
            examples = dict(self._edge_examples)
            holds = {s: list(v) for s, v in self.holds.items()}
            long_holds = list(self.long_holds)
        inversions = []
        for comp in self._cycles(edges):
            comp_set = set(comp)
            witness = [
                {"edge": f"{sites.get(a, a)} -> {sites.get(b, b)}",
                 "thread": examples.get((a, b), ("?", []))[0],
                 "stack": examples.get((a, b), ("?", []))[1]}
                for (a, b) in sorted(edges)
                if a in comp_set and b in comp_set
            ]
            inversions.append({
                "locks": sorted({sites.get(s, str(s)) for s in comp}),
                "witnessed_edges": witness,
            })
        return {
            "locks_instrumented": len(sites),
            "edges_observed": len(edges),
            "inversions": inversions,
            "long_holds": long_holds,
            "hold_stats": {
                s: {"holds": v[0], "total_s": round(v[1], 4), "max_s": round(v[2], 4)}
                for s, v in sorted(holds.items(),
                                   key=lambda kv: -kv[1][2])
            },
        }


class _SanLockBase:
    """Instrumented wrapper around a real lock primitive.  Unknown
    attributes (``_at_fork_reinit``, ``_is_owned``, ``_release_save``...)
    delegate to the inner lock so Condition/fork integration keeps
    working; the delegated forms bypass hold-timing, never correctness."""

    _inner_factory = staticmethod(_REAL_LOCK)

    def __init__(self, san: LockSanitizer, site: str):
        self._inner = self._inner_factory()
        self._san = san
        self._site = site
        self._serial = san.register(site)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._san.on_acquired(self._serial, self._site)
        return ok

    acquire_lock = acquire  # ancient alias some libs still use

    def release(self):
        self._san.on_released(self._serial)
        self._inner.release()

    release_lock = release

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def __repr__(self):
        return f"<sanitized {type(self._inner).__name__} from {self._site}>"


class _SanLock(_SanLockBase):
    _inner_factory = staticmethod(_REAL_LOCK)


class _SanRLock(_SanLockBase):
    _inner_factory = staticmethod(_REAL_RLOCK)

    # Condition integration: wait() must not be timed as one giant hold —
    # the lock is RELEASED for the duration.  These mirror RLock's own
    # protocol with the bookkeeping kept in step.
    def _release_save(self):
        self._san.on_released_fully(self._serial)
        return self._inner._release_save()

    def _acquire_restore(self, state):
        self._inner._acquire_restore(state)
        self._san.on_acquired(self._serial, self._site)

    def _is_owned(self):
        return self._inner._is_owned()


def _in_package(path: str) -> bool:
    return "fedml_tpu/" in path


def install(long_hold_s: float | None = None) -> LockSanitizer:
    """Patch ``threading.Lock``/``RLock`` with the instrumenting factories.
    Idempotent; returns the process sanitizer."""
    global _ACTIVE
    if _ACTIVE is not None:
        return _ACTIVE
    if long_hold_s is None:
        long_hold_s = float(os.environ.get(ENV_HOLD, "0.5"))
    san = LockSanitizer(long_hold_s=long_hold_s)

    def make_lock():
        path, site = _creation_site()
        return _SanLock(san, site) if _in_package(path) else _REAL_LOCK()

    def make_rlock():
        path, site = _creation_site()
        return _SanRLock(san, site) if _in_package(path) else _REAL_RLOCK()

    threading.Lock = make_lock
    threading.RLock = make_rlock
    _ACTIVE = san
    atexit.register(_dump_on_exit)
    return san


def uninstall() -> None:
    """Restore the real factories (already-created instrumented locks keep
    working — they wrap real primitives)."""
    global _ACTIVE
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    _ACTIVE = None


def active() -> "LockSanitizer | None":
    return _ACTIVE


def maybe_install_from_env() -> "LockSanitizer | None":
    """The one public entry point for harness code: a strict no-op unless
    ``FEDML_TPU_LOCKSAN=1``."""
    if os.environ.get(ENV_FLAG) == "1":
        return install()
    return None


def _dump_on_exit() -> None:
    san = _ACTIVE
    if san is None:
        return
    rep = san.report()
    path = os.environ.get(ENV_REPORT)
    if path:
        try:
            with open(path, "w") as f:
                json.dump(rep, f, indent=1)
                f.write("\n")
        except OSError:
            path = None
    if not path:
        summary = {k: rep[k] for k in ("locks_instrumented", "edges_observed")}
        summary["inversions"] = len(rep["inversions"])
        summary["long_holds"] = len(rep["long_holds"])
        print(f"FEDML_TPU_LOCKSAN report: {json.dumps(summary)}", file=sys.stderr)
        for inv in rep["inversions"]:
            print(f"LOCKSAN INVERSION: {inv['locks']}", file=sys.stderr)
