"""Static analysis for the framework's hard-won invariants (``fedml-tpu lint``).

A stdlib-``ast`` engine (no third-party linter deps) with a rule-plugin
architecture: :mod:`.engine` parses every module of a package once into
:class:`~fedml_tpu.analysis.engine.ModuleInfo` and hands the shared walk to
per-rule visitors under :mod:`.rules`.  Findings carry ``file:line``, a rule
id, a severity, and a stable key; a checked-in suppression baseline
(``baseline.json``, shipped empty) plus inline
``# graftlint: disable=GLxxx(reason)`` comments are the only two ways to
silence one.

Rules (each encodes a failure mode this codebase hit for real):

====== ======================================================================
GL001  flag-registry: every ``cfg.extra`` flag read must be declared in
       ``core/flags.py`` (type, default, doc); dead declarations and legacy
       access idioms are findings too.
GL002  jit-purity: host side effects (wall clocks, np.random, logging,
       global metrics, nonlocal mutation) inside functions handed to
       ``jax.jit``/``pjit``/``lax.scan``/``pallas_call``.
GL003  donation-safety: reading a variable after it was passed in a
       ``donate_argnums`` position of a jitted call (donated buffers are
       invalid — and corrupt the heap on XLA:CPU, see ``sim/engine.py``).
GL004  lock-discipline: attributes guarded by a ``threading.Lock`` in one
       method but accessed without it elsewhere in the same class.
GL005  metric-namespace: every global-registry metric family must match
       ``fedml_[a-z0-9_]+`` with valid label names.
====== ======================================================================

Entry points: ``python -m fedml_tpu.cli lint`` and
:func:`fedml_tpu.analysis.engine.run_lint` (the tier-1 test wraps the
latter over the real package).
"""

from .engine import LintResult, run_lint  # noqa: F401
from .findings import Finding  # noqa: F401
