"""GL010 — host-sync: no implicit device→host transfers on hot round paths.

The ROADMAP's device-resident-rounds work (``jit(scan)`` chunks with
device-side double buffering) is only safe if the steady-state round loop
provably contains no hidden host synchronizations — FedJAX's core lesson
(PAPERS.md 2108.02117) is that TPU simulation speed lives or dies on
keeping the round loop free of host round-trips.  This rule enforces the
static half (TRACESAN, ``analysis/tracesan.py``, is the runtime half):
inside functions *reachable from a hot-path root* it flags every
construct that forces the device to materialize a value on the host:

- ``float()`` / ``int()`` / ``bool()`` on a value produced by a jax
  computation (each blocks on the device and ships one scalar);
- ``.item()`` / ``np.asarray`` / ``np.array`` on a device value;
- ``jax.device_get`` / ``.block_until_ready()`` anywhere on the hot path
  — legitimate *annotated measurement sites* (the one chunk-end sync, the
  round-boundary metric export) carry a suppression naming the invariant;
- iterating a device value or branching/comparing on one (``if loss <
  0.5:``) — both force materialization (``is None`` / ``is not None``
  stay clean, they are structural).

**Hot-path roots** live in :data:`HOT_PATH_ROOTS` — a registry keyed by
path suffix naming the entry points of the steady-state loop: the
simulator round/chunk functions, the population cohort round, the server
fold/finalize path, and the serving batcher execute.  Reachability
extends GL002/GL006's traced-callable resolution to host code: from each
root, local calls (bare module-level functions and ``self.method``) are
followed within the module; nested ``def``s are skipped (they are traced
functions — GL002/GL006 territory).

**Device-value taint** is the repo's own conventions, applied in source
order: results of ``jnp.*`` / ``jax.*`` calls, and results of calling any
``*_fn`` name (``self._round_fn``, ``pop.round_fn``, ``self._eval_fn``,
a local ``fn`` — the package-wide naming convention for compiled
programs).  ``jax.device_get`` results are HOST values — they untaint,
so the post-sync metric unpacking loop stays clean.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from ..engine import Finding, ModuleInfo, Rule, dotted_name

#: path suffix -> qualified names ("Class.method" or "function") that anchor
#: the hot round path in that module.  New steady-state entry points (a
#: device-resident cohort loop, a new serving execute path) register here.
HOT_PATH_ROOTS: dict[str, set[str]] = {
    "sim/engine.py": {
        "MeshSimulator.run_rounds",
        "MeshSimulator.run_round",
        "MeshSimulator.evaluate",
        "MeshSimulator._run_one_population_round",
    },
    "cross_silo/server.py": {
        "FedMLAggregator.fold",
        "FedMLAggregator.fold_partial",
        "FedMLAggregator.ingest_streaming",
        "FedMLAggregator.aggregate",
    },
    "cross_silo/async_server.py": {
        "AsyncFedMLServerManager.handle_message_receive_model",
        "AsyncFedMLServerManager._close_virtual_round",
    },
    "serving/batcher.py": {
        "MicroBatcher._execute",
    },
}


def register_hot_path(path_suffix: str, qualname: str) -> None:
    """Extension point: add one hot-path root (used by out-of-tree engines
    that want their round loop under the same contract)."""
    HOT_PATH_ROOTS.setdefault(path_suffix, set()).add(qualname)


#: dotted-chain prefixes whose call results live on device
_PRODUCER_PREFIXES = ("jnp.", "jax.numpy.", "jax.lax.", "jax.random.",
                      "jax.tree_util.", "jax.tree.")
#: numpy materializers — a device argument forces a full transfer
_NP_MATERIALIZERS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                     "onp.asarray", "onp.array"}
_SCALARIZERS = {"float", "int", "bool"}


#: jax.* calls returning HOST metadata (treedefs), not device values —
#: comparing/branching on them is structural, not a sync
_HOST_METADATA_CALLS = {"jax.device_get", "jax.tree_util.tree_structure",
                        "jax.tree.structure"}


def _is_producer_chain(chain: str) -> bool:
    if chain.startswith(_PRODUCER_PREFIXES):
        return chain not in _HOST_METADATA_CALLS
    tail = chain.rsplit(".", 1)[-1]
    # the repo-wide convention: compiled programs are bound to *_fn names
    return tail == "fn" or tail.endswith("_fn")


def _collect_functions(tree: ast.Module) -> dict[str, ast.FunctionDef]:
    """qualname -> def for module-level functions and class methods."""
    out: dict[str, ast.FunctionDef] = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out[f"{node.name}.{sub.name}"] = sub
    return out


def _local_calls(fn: ast.FunctionDef, qualname: str,
                 funcs: dict[str, ast.FunctionDef]) -> set[str]:
    """Qualnames of same-module callees: bare names and ``self.method``."""
    cls = qualname.rsplit(".", 1)[0] if "." in qualname else None
    out: set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id in funcs:
            out.add(f.id)
        elif (cls and isinstance(f, ast.Attribute)
              and isinstance(f.value, ast.Name) and f.value.id == "self"
              and f"{cls}.{f.attr}" in funcs):
            out.add(f"{cls}.{f.attr}")
    return out


class _HotScan:
    """Source-order taint + sink scan over one hot-path function body."""

    def __init__(self) -> None:
        self.tainted: set[str] = set()
        self.hits: list[tuple[int, str]] = []

    # -- taint ---------------------------------------------------------------
    def expr_taint(self, e: Optional[ast.AST]) -> bool:
        if e is None:
            return False
        if isinstance(e, ast.Name):
            return e.id in self.tainted
        if isinstance(e, ast.Call):
            chain = dotted_name(e.func)
            if chain == "jax.device_get" or chain.endswith(".device_get"):
                return False  # explicit sync: result is a host value
            if _is_producer_chain(chain):
                return True
            # method call on a tainted receiver (metrics.items(), x.astype())
            if isinstance(e.func, ast.Attribute):
                return self.expr_taint(e.func.value)
            return False
        if isinstance(e, (ast.Attribute, ast.Subscript, ast.Starred)):
            return self.expr_taint(e.value)
        if isinstance(e, ast.BinOp):
            return self.expr_taint(e.left) or self.expr_taint(e.right)
        if isinstance(e, ast.UnaryOp):
            return self.expr_taint(e.operand)
        if isinstance(e, (ast.Tuple, ast.List)):
            return any(self.expr_taint(v) for v in e.elts)
        if isinstance(e, ast.IfExp):
            return self.expr_taint(e.body) or self.expr_taint(e.orelse)
        if isinstance(e, ast.Compare):
            # `loss < 0.5` over a device value is tainted (the comparison
            # itself would have to materialize) — `is`/`is not` structural
            # checks are filtered by _static_predicate at the branch sink
            return self.expr_taint(e.left) or any(
                self.expr_taint(c) for c in e.comparators)
        if isinstance(e, ast.BoolOp):
            return any(self.expr_taint(v) for v in e.values)
        return False

    def _taint_target(self, t: ast.AST, on: bool) -> None:
        if isinstance(t, ast.Name):
            (self.tainted.add if on else self.tainted.discard)(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._taint_target(el, on)
        elif isinstance(t, ast.Starred):
            self._taint_target(t.value, on)

    # -- sinks ---------------------------------------------------------------
    def _static_predicate(self, test: ast.AST) -> bool:
        """`x is None` / `is not` comparisons are structural, not syncs."""
        return (isinstance(test, ast.Compare)
                and all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops))

    def check_expr(self, e: Optional[ast.AST]) -> None:
        if e is None:
            return
        for node in ast.walk(e):
            # comprehension targets inherit the iterable's taint first, so
            # `{k: float(v) for k, v in metrics.items()}` sees tainted v
            if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                 ast.GeneratorExp)):
                for gen in node.generators:
                    if self.expr_taint(gen.iter):
                        self._taint_target(gen.target, True)
            if not isinstance(node, ast.Call):
                continue
            chain = dotted_name(node.func)
            tail = chain.rsplit(".", 1)[-1] if chain else ""
            arg0 = node.args[0] if node.args else None
            if chain == "jax.device_get" or chain.endswith(".device_get"):
                self.hits.append((node.lineno,
                                  "explicit host sync jax.device_get()"))
            elif tail == "block_until_ready" and isinstance(node.func, ast.Attribute):
                self.hits.append((node.lineno, ".block_until_ready() host sync"))
            elif chain in _SCALARIZERS and len(node.args) == 1 \
                    and self.expr_taint(arg0):
                self.hits.append((node.lineno,
                                  f"implicit device->host sync {chain}() on a "
                                  "jax value"))
            elif tail == "item" and isinstance(node.func, ast.Attribute) \
                    and self.expr_taint(node.func.value):
                self.hits.append((node.lineno,
                                  ".item() forces a device->host transfer"))
            elif chain in _NP_MATERIALIZERS and node.args \
                    and self.expr_taint(arg0):
                self.hits.append((node.lineno,
                                  f"{chain}() materializes a device value on "
                                  "the host"))

    # -- statements ----------------------------------------------------------
    def scan(self, body: list[ast.stmt]) -> None:
        for st in body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue  # nested defs are traced functions — GL002's domain
            if isinstance(st, ast.Assign):
                self.check_expr(st.value)
                on = self.expr_taint(st.value)
                for t in st.targets:
                    self._taint_target(t, on)
            elif isinstance(st, (ast.AnnAssign, ast.AugAssign)):
                self.check_expr(st.value)
                if st.value is not None:
                    self._taint_target(st.target, self.expr_taint(st.value))
            elif isinstance(st, ast.For):
                self.check_expr(st.iter)
                if self.expr_taint(st.iter):
                    self.hits.append((st.lineno,
                                      "iterating a device value forces "
                                      "materialization"))
                    self._taint_target(st.target, True)
                self.scan(st.body)
                self.scan(st.orelse)
            elif isinstance(st, (ast.If, ast.While)):
                self.check_expr(st.test)
                if self.expr_taint(st.test) and not self._static_predicate(st.test):
                    self.hits.append((st.lineno,
                                      "branching/comparing on a device value "
                                      "forces a host sync"))
                self.scan(st.body)
                self.scan(st.orelse)
            elif isinstance(st, ast.With):
                for item in st.items:
                    self.check_expr(item.context_expr)
                self.scan(st.body)
            elif isinstance(st, ast.Try):
                self.scan(st.body)
                for h in st.handlers:
                    self.scan(h.body)
                self.scan(st.orelse)
                self.scan(st.finalbody)
            elif isinstance(st, (ast.Expr, ast.Return)):
                self.check_expr(st.value)
            elif isinstance(st, (ast.Raise, ast.Assert)):
                self.check_expr(getattr(st, "exc", None) or getattr(st, "test", None))


class HostSyncRule(Rule):
    id = "GL010"
    title = "implicit device->host sync on a hot round path"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        roots: set[str] = set()
        for suffix, names in HOT_PATH_ROOTS.items():
            if mod.relpath.endswith(suffix):
                roots |= names
        if not roots:
            return []
        funcs = _collect_functions(mod.tree)
        reachable: set[str] = set()
        frontier = [r for r in roots if r in funcs]
        while frontier:
            qn = frontier.pop()
            if qn in reachable:
                continue
            reachable.add(qn)
            frontier.extend(_local_calls(funcs[qn], qn, funcs) - reachable)
        findings: list[Finding] = []
        for qn in sorted(reachable):
            scan = _HotScan()
            scan.scan(funcs[qn].body)
            for line, what in scan.hits:
                findings.append(Finding(
                    self.id, mod.relpath, line,
                    f"{what} inside hot-path function {qn!r} (reachable from "
                    f"a HOT_PATH_ROOTS entry) — keep the steady-state round "
                    f"loop free of host round-trips; annotate deliberate "
                    f"measurement sites with a suppression naming the "
                    f"invariant",
                    symbol=f"{qn}:L{line}"))
        return findings
