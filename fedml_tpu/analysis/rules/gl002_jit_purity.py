"""GL002 — jit-purity: no host side effects inside traced functions.

A function handed to ``jax.jit`` / ``pjit`` / ``jax.lax.scan`` /
``pl.pallas_call`` runs ONCE at trace time; host side effects inside it
silently execute at compile time (wall clocks measure tracing, metrics
record once, ``np.random`` freezes a single draw into the program) — the
exact class of bug FedJAX's design notes warn a JAX FL stack about.

Flagged inside a traced function body:

- host clocks: ``time.time/perf_counter/monotonic/sleep``, ``datetime.now``;
- host randomness: ``np.random.*`` / ``random.*`` (JAX keys are fine);
- logging/printing: ``print``, ``log.*``/``logger.*``/``logging.*``;
- global metrics: calls on module-level objects created from
  ``REGISTRY.counter/gauge/histogram``, or any ``REGISTRY.*`` chain;
- ``global`` / ``nonlocal`` declarations (trace-time host mutation).

Allowlisted (ISSUE 20 satellite) — instrumentation that is *deliberately*
trace-time and mutates nothing observable by the program:

- ``REGISTRY.get(...)`` — the read-only registry lookup the cost-model
  join uses (``REGISTRY.get`` returns an existing family; it registers
  nothing and increments nothing, so recording once at trace time is the
  correct behavior, not a frozen side effect);
- ``<...>profiler.note_program/maybe_start/maybe_stop(...)`` (and the
  ``attributor`` spelling) — the profiler-window bookkeeping hooks; the
  attribution pipeline is designed around at-trace-time notes keyed by
  program name, so a note inside traced code is its intended use.

The rule resolves the traced callable statically when it is a lambda, a
local ``def`` in the enclosing scope, or a module-level ``def``; dynamic
targets (``self._fn``, call results) are out of scope — the donation rule
and runtime behavior cover those.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from ..engine import Finding, ModuleInfo, Rule, dotted_name

#: call-chain suffixes that enter tracing with the callable as first arg
JIT_ENTRY_SUFFIXES = ("jax.jit", "jit", "pjit", "jax.lax.scan", "lax.scan",
                      "pallas_call", "pl.pallas_call")

_CLOCK_CALLS = {"time.time", "time.perf_counter", "time.monotonic", "time.sleep",
                "time.process_time", "datetime.now", "datetime.utcnow",
                "datetime.datetime.now", "datetime.datetime.utcnow"}
_LOG_RECEIVERS = {"log", "logger", "logging"}
_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception", "critical"}
_METRIC_METHODS = {"observe", "inc", "set", "labels"}
#: deliberately trace-time instrumentation (see module docstring)
_PROFILER_METHODS = {"note_program", "maybe_start", "maybe_stop"}
_PROFILER_RECEIVERS = ("profiler", "attributor")
_REGISTRY_READONLY = {"get"}


def _is_jit_entry(fn_chain: str) -> bool:
    return any(fn_chain == s or fn_chain.endswith("." + s) for s in JIT_ENTRY_SUFFIXES)


def module_metric_names(tree: ast.Module) -> set[str]:
    """Module-level names bound to REGISTRY.counter/gauge/histogram(...)."""
    out: set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            chain = dotted_name(node.value.func)
            if "REGISTRY." in chain and chain.rsplit(".", 1)[-1] in (
                    "counter", "gauge", "histogram"):
                out.update(t.id for t in node.targets if isinstance(t, ast.Name))
    return out


class _ImpurityScan(ast.NodeVisitor):
    def __init__(self, metric_names: set[str]):
        self.metric_names = metric_names
        self.hits: list[tuple[int, str]] = []  # (line, description)

    def visit_Global(self, node: ast.Global) -> None:
        self.hits.append((node.lineno, f"`global {', '.join(node.names)}` mutation"))

    def visit_Nonlocal(self, node: ast.Nonlocal) -> None:
        self.hits.append((node.lineno, f"`nonlocal {', '.join(node.names)}` mutation"))

    def _is_allowlisted(self, node: ast.Call, chain: str, tail: str) -> bool:
        # read-only registry lookup (cost-model join): registers/mutates nothing
        if "REGISTRY." in chain and tail in _REGISTRY_READONLY:
            return True
        # profiler-window bookkeeping on a profiler/attributor receiver:
        # at-trace-time notes are the attribution pipeline's intended use
        if isinstance(node.func, ast.Attribute) and tail in _PROFILER_METHODS:
            recv = dotted_name(node.func.value).lower()
            return any(r in recv for r in _PROFILER_RECEIVERS)
        return False

    def visit_Call(self, node: ast.Call) -> None:
        chain = dotted_name(node.func)
        tail = chain.rsplit(".", 1)[-1] if chain else ""
        if self._is_allowlisted(node, chain, tail):
            # allowlisted call itself is fine — but its ARGUMENTS still trace,
            # so keep walking for impurities nested inside them
            self.generic_visit(node)
            return
        if chain in _CLOCK_CALLS or (chain and any(
                chain.endswith("." + c) for c in _CLOCK_CALLS)):
            self.hits.append((node.lineno, f"host clock call {chain}()"))
        elif chain == "print":
            self.hits.append((node.lineno, "print()"))
        elif chain.startswith(("np.random.", "numpy.random.", "random.")):
            self.hits.append((node.lineno, f"host randomness {chain}()"))
        elif "REGISTRY." in chain:
            self.hits.append((node.lineno, f"global metrics registry call {chain}()"))
        elif isinstance(node.func, ast.Attribute) and tail in _LOG_METHODS \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in _LOG_RECEIVERS:
            self.hits.append((node.lineno, f"logging call {chain}()"))
        elif isinstance(node.func, ast.Attribute) and tail in _METRIC_METHODS \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in self.metric_names:
            self.hits.append(
                (node.lineno, f"metric mutation {chain}() on a registry family"))
        self.generic_visit(node)


def _local_defs(scope_body: list[ast.stmt]) -> dict[str, ast.AST]:
    """name -> FunctionDef/Lambda bound directly in this statement list."""
    out: dict[str, ast.AST] = {}
    for stmt in scope_body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[stmt.name] = stmt
        elif isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Lambda):
            out.update({t.id: stmt.value for t in stmt.targets
                        if isinstance(t, ast.Name)})
    return out


class JitPurityRule(Rule):
    id = "GL002"
    title = "host side effects inside jit/pjit/scan/pallas_call traced functions"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        metric_names = module_metric_names(mod.tree)
        module_defs = _local_defs(mod.tree.body)
        findings: list[Finding] = []

        def resolve(candidate: ast.AST, scopes: list[dict[str, ast.AST]]) -> Optional[ast.AST]:
            if isinstance(candidate, ast.Lambda):
                return candidate
            if isinstance(candidate, ast.Name):
                for defs in reversed(scopes):
                    if candidate.id in defs:
                        return defs[candidate.id]
            return None

        seen: set[tuple[str, int, str]] = set()

        def scan_target(target: ast.AST, entry: str, entry_line: int, fn_name: str) -> None:
            scanner = _ImpurityScan(metric_names)
            if isinstance(target, ast.Lambda):
                scanner.visit(target.body)
            else:  # FunctionDef: the whole body, nested closures included —
                for stmt in target.body:  # they trace with it
                    scanner.visit(stmt)
            for line, what in scanner.hits:
                if (fn_name, line, what) in seen:
                    continue
                seen.add((fn_name, line, what))
                findings.append(Finding(
                    self.id, mod.relpath, line,
                    f"{what} inside {fn_name!r}, traced by {entry} at line "
                    f"{entry_line} — hoist host side effects out of traced code",
                    symbol=f"{fn_name}:L{line}"))

        def shallow_walk(stmt: ast.stmt):
            """Walk a statement without descending into nested function/class
            bodies — those belong to the recursive scope walk below."""
            stack: list[ast.AST] = [stmt]
            while stack:
                node = stack.pop()
                yield node
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                          ast.ClassDef)):
                        continue
                    stack.append(child)

        def walk_scope(body: list[ast.stmt], scopes: list[dict[str, ast.AST]]) -> None:
            defs = _local_defs(body)
            scopes = scopes + [defs]
            for stmt in body:
                # decorator form: @jax.jit / @partial(jax.jit, ...)
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for dec in stmt.decorator_list:
                        chain = dotted_name(dec if not isinstance(dec, ast.Call) else dec.func)
                        inner = ""
                        if isinstance(dec, ast.Call) and chain.endswith("partial") and dec.args:
                            inner = dotted_name(dec.args[0])
                        if _is_jit_entry(chain) or _is_jit_entry(inner):
                            scan_target(stmt, chain or inner, stmt.lineno, stmt.name)
                for node in shallow_walk(stmt):
                    if isinstance(node, ast.Call) and _is_jit_entry(dotted_name(node.func)):
                        if not node.args:
                            continue
                        target = resolve(node.args[0], scopes)
                        if target is None:
                            continue
                        fn_name = (node.args[0].id if isinstance(node.args[0], ast.Name)
                                   else "<lambda>")
                        scan_target(target, dotted_name(node.func), node.lineno, fn_name)
                # recurse into nested function bodies with their scope chain
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                    walk_scope(stmt.body, scopes)

        walk_scope(mod.tree.body, [module_defs])
        return findings
