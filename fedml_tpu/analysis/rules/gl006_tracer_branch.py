"""GL006 — no Python-side control flow on traced values.

Inside a function traced by ``jax.jit`` / ``pjit`` / ``jax.lax.scan`` /
``pallas_call``, the arguments are TRACERS: ``if x:`` (or ``while x:``,
``x if c else y``, ``assert x``) forces a concrete boolean out of an
abstract value and raises ``TracerBoolConversionError`` at trace time — or
worse, when the value happens to be weakly-typed-concrete at trace time,
silently BAKES one branch into the compiled program (the classic
"conditional evaluated once, at compile time" bug).  Use ``jax.lax.cond`` /
``jnp.where`` / ``lax.select`` instead.

The rule reuses GL002's static resolution of traced callables (inline
lambdas, local/module ``def``s handed to a jit entry, decorator and
``partial`` forms).  Within a traced body it taints the function's
parameters (``self``/``cls`` excluded) and propagates through assignments,
tuple unpacking, and ``for`` targets; a branch condition containing a
tainted name is a finding.

Deliberately NOT flagged — these are static (Python-value) predicates on
structure, not on traced data:

- ``x is None`` / ``x is not None`` (optional-pytree dispatch, e.g. the
  engine's stateless-algorithm branch);
- ``isinstance(x, ...)`` / ``callable(x)`` / ``hasattr(x, ...)``;
- ``len(x)`` and the static array attributes ``x.shape`` / ``x.ndim`` /
  ``x.size`` / ``x.dtype`` (shape math is resolved at trace time by
  design).
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from ..engine import Finding, ModuleInfo, Rule, dotted_name
from .gl002_jit_purity import _is_jit_entry, _local_defs

_STATIC_ATTRS = {"shape", "ndim", "size", "dtype"}
_STATIC_CALLS = {"isinstance", "callable", "hasattr", "len", "type"}


def _params_of(target: ast.AST) -> list[str]:
    if isinstance(target, ast.Lambda):
        args = target.args
    elif isinstance(target, (ast.FunctionDef, ast.AsyncFunctionDef)):
        args = target.args
    else:
        return []
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return [n for n in names if n not in ("self", "cls")]


class _TaintedUse(ast.NodeVisitor):
    """Finds Load uses of tainted names in an expression, skipping the
    static-predicate forms documented in the module docstring."""

    def __init__(self, tainted: set[str]):
        self.tainted = tainted
        self.hits: list[tuple[int, str]] = []

    def visit_Compare(self, node: ast.Compare) -> None:
        # `x is None` / `x is not None`: identity against None is a Python
        # structure test, never a tracer bool
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops) and (
            any(isinstance(c, ast.Constant) and c.value is None
                for c in [node.left, *node.comparators])
        ):
            return
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        chain = dotted_name(node.func)
        if chain in _STATIC_CALLS:
            return  # len()/isinstance()/... of a tracer is a static value
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr in _STATIC_ATTRS:
            return  # x.shape / x.ndim / ... are static metadata
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load) and node.id in self.tainted:
            self.hits.append((node.lineno, node.id))


def _names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _bind_targets(target: ast.AST, out: set[str]) -> None:
    if isinstance(target, ast.Name):
        out.add(target.id)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            _bind_targets(elt, out)
    elif isinstance(target, ast.Starred):
        _bind_targets(target.value, out)


class _TracedBodyScan:
    """Taint-propagating, SOURCE-ORDER walk of one traced function body
    (taint must flow forward: ``y = x + 1`` taints ``y`` only for the
    statements after it)."""

    def __init__(self, fn_name: str):
        self.fn_name = fn_name
        self.hits: list[tuple[int, str]] = []

    def _check(self, test: ast.AST, tainted: set[str], kind: str) -> None:
        v = _TaintedUse(tainted)
        v.visit(test)
        for line, name in v.hits:
            self.hits.append((
                line,
                f"Python {kind} on {name!r}, which derives from a traced "
                f"argument of {self.fn_name!r}"))

    def _expr(self, expr: ast.AST, tainted: set[str]) -> None:
        """Conditional expressions can hide anywhere in an expression."""
        for node in ast.walk(expr):
            if isinstance(node, ast.IfExp):
                self._check(node.test, tainted, "conditional expression")

    def scan(self, body: list[ast.stmt], tainted: set[str]) -> None:
        tainted = set(tainted)
        for stmt in body:
            if isinstance(stmt, ast.If):
                self._check(stmt.test, tainted, "`if` branch")
                self.scan(stmt.body, tainted)
                self.scan(stmt.orelse, tainted)
            elif isinstance(stmt, ast.While):
                self._check(stmt.test, tainted, "`while` loop")
                self.scan(stmt.body, tainted)
                self.scan(stmt.orelse, tainted)
            elif isinstance(stmt, ast.Assert):
                self._check(stmt.test, tainted, "`assert`")
            elif isinstance(stmt, ast.Assign):
                self._expr(stmt.value, tainted)
                if _names_in(stmt.value) & tainted:
                    for t in stmt.targets:
                        _bind_targets(t, tainted)
            elif isinstance(stmt, ast.AugAssign):
                self._expr(stmt.value, tainted)
                if isinstance(stmt.target, ast.Name) and (
                        _names_in(stmt.value) & tainted
                        or stmt.target.id in tainted):
                    tainted.add(stmt.target.id)
            elif isinstance(stmt, ast.For):
                self._expr(stmt.iter, tainted)
                if _names_in(stmt.iter) & tainted:
                    _bind_targets(stmt.target, tainted)
                self.scan(stmt.body, tainted)
                self.scan(stmt.orelse, tainted)
            elif isinstance(stmt, ast.With):
                for item in stmt.items:
                    self._expr(item.context_expr, tainted)
                self.scan(stmt.body, tainted)
            elif isinstance(stmt, ast.Try):
                self.scan(stmt.body, tainted)
                for h in stmt.handlers:
                    self.scan(h.body, tainted)
                self.scan(stmt.orelse, tainted)
                self.scan(stmt.finalbody, tainted)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a nested def traces with the outer function; its params
                # are tracers too (the vmap/scan body idiom)
                self.scan(stmt.body, tainted | set(_params_of(stmt)))
            elif isinstance(stmt, (ast.Return, ast.Expr)) and stmt.value is not None:
                self._expr(stmt.value, tainted)


class TracerBranchRule(Rule):
    id = "GL006"
    title = "Python-side conditional on a traced value inside jit/scan"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        findings: list[Finding] = []
        module_defs = _local_defs(mod.tree.body)
        seen: set[tuple[str, int, str]] = set()

        def resolve(candidate: ast.AST, scopes: list[dict]) -> Optional[ast.AST]:
            if isinstance(candidate, ast.Lambda):
                return candidate
            if isinstance(candidate, ast.Name):
                for defs in reversed(scopes):
                    if candidate.id in defs:
                        return defs[candidate.id]
            return None

        def scan_target(target: ast.AST, entry: str, entry_line: int,
                        fn_name: str) -> None:
            tainted = set(_params_of(target))
            scanner = _TracedBodyScan(fn_name)
            if isinstance(target, ast.Lambda):
                scanner._expr(target.body, tainted)
            else:
                scanner.scan(target.body, tainted)
            for line, what in scanner.hits:
                key = (fn_name, line, what)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(Finding(
                    self.id, mod.relpath, line,
                    f"{what} — traced by {entry} at line {entry_line}; a "
                    "tracer has no Python truth value (or silently bakes one "
                    "branch in at trace time) — use jax.lax.cond/select or "
                    "jnp.where",
                    symbol=f"{fn_name}:L{line}"))

        def shallow_walk(stmt: ast.stmt):
            stack: list[ast.AST] = [stmt]
            while stack:
                node = stack.pop()
                yield node
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                          ast.ClassDef)):
                        continue
                    stack.append(child)

        def walk_scope(body: list[ast.stmt], scopes: list[dict]) -> None:
            defs = _local_defs(body)
            scopes = scopes + [defs]
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for dec in stmt.decorator_list:
                        chain = dotted_name(dec if not isinstance(dec, ast.Call) else dec.func)
                        inner = ""
                        if isinstance(dec, ast.Call) and chain.endswith("partial") and dec.args:
                            inner = dotted_name(dec.args[0])
                        if _is_jit_entry(chain) or _is_jit_entry(inner):
                            scan_target(stmt, chain or inner, stmt.lineno, stmt.name)
                for node in shallow_walk(stmt):
                    if isinstance(node, ast.Call) and _is_jit_entry(dotted_name(node.func)):
                        if not node.args:
                            continue
                        target = resolve(node.args[0], scopes)
                        if target is None:
                            continue
                        fn_name = (node.args[0].id if isinstance(node.args[0], ast.Name)
                                   else "<lambda>")
                        scan_target(target, dotted_name(node.func), node.lineno, fn_name)
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                    walk_scope(stmt.body, scopes)

        walk_scope(mod.tree.body, [module_defs])
        return findings
