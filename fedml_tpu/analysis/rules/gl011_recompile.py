"""GL011 — recompile-hazard: silent per-call retracing of jitted programs.

XLA caches compiled programs by (callable identity, static argument
values, argument avals).  Two idioms silently defeat the cache and turn
the steady-state round loop into a compile loop:

1. **Re-wrapping inside a loop body** — ``jax.jit(f)`` (or ``pjit`` /
   ``lax.scan`` / ``pallas_call``) evaluated inside a ``for``/``while``
   body creates a *fresh* wrapper object each iteration, so every
   iteration traces and compiles from scratch.  Hoist the wrapper (or
   memoize it, like ``MeshSimulator._multi_round_fns``).

2. **Per-call-varying Python scalars reaching a jitted callable** — a
   raw loop index, cohort size, ``len()`` of a growing structure, or a
   wall-clock read passed positionally to a jitted function is hashed
   into the static trace for weak types or retraces on every new value.
   The disciplined forms are: convert at the callsite
   (``jnp.int32(r)`` — a device scalar, one program), or declare the
   argument static at the wrap site (``static_argnums`` /
   ``static_argnames`` — each distinct value is a deliberate variant),
   or bake it into a hashable ``functools.partial``.

The rule resolves jitted callables the same way GL002 resolves traced
ones: ``f = jax.jit(g, ...)`` assignments and ``@jax.jit`` /
``@partial(jax.jit, ...)`` decorations, per scope.  A wrap that declares
``static_argnums``/``static_argnames`` is treated as disciplined and its
callsites are not checked (the approximation is documented: the rule
checks discipline exists, not the exact position mapping).  *Varying*
expressions are loop targets of enclosing ``for`` loops, names augmented
inside a loop (``i += 1`` counters), and direct ``len(...)`` /
``time.*()`` reads — plus any arithmetic over those.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..engine import Finding, ModuleInfo, Rule, dotted_name
from .gl002_jit_purity import JIT_ENTRY_SUFFIXES, _is_jit_entry

_TIME_CALLS = ("time.time", "time.perf_counter", "time.monotonic",
               "time.process_time")
_STATIC_KWARGS = {"static_argnums", "static_argnames"}


def _wrap_chain(call: ast.Call) -> str:
    """The jit-entry chain of a wrap call, seeing through
    ``partial(jax.jit, ...)``."""
    chain = dotted_name(call.func)
    if chain.endswith("partial") and call.args:
        inner = dotted_name(call.args[0])
        if _is_jit_entry(inner):
            return inner
    return chain


def _has_static_discipline(call: ast.Call) -> bool:
    return any(kw.arg in _STATIC_KWARGS for kw in call.keywords)


class _JittedNames:
    """name -> has_static_discipline, for one lexical scope."""

    def __init__(self) -> None:
        self.names: dict[str, bool] = {}

    def harvest(self, body: list[ast.stmt]) -> None:
        for st in body:
            if isinstance(st, ast.Assign) and isinstance(st.value, ast.Call):
                if _is_jit_entry(_wrap_chain(st.value)):
                    disciplined = _has_static_discipline(st.value)
                    for t in st.targets:
                        if isinstance(t, ast.Name):
                            self.names[t.id] = disciplined
            elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in st.decorator_list:
                    if isinstance(dec, ast.Call):
                        if _is_jit_entry(_wrap_chain(dec)):
                            self.names[st.name] = _has_static_discipline(dec)
                    elif _is_jit_entry(dotted_name(dec)):
                        self.names[st.name] = False


class _FnScan:
    """Per-function walk tracking loop nesting and varying names."""

    def __init__(self, rule: "RecompileHazardRule", mod: ModuleInfo,
                 jitted: dict[str, bool], fn_name: str):
        self.rule = rule
        self.mod = mod
        self.jitted = jitted
        self.fn_name = fn_name
        self.varying: set[str] = set()
        self.hits: list[tuple[int, str]] = []

    def _is_varying(self, e: ast.AST) -> bool:
        if isinstance(e, ast.Name):
            return e.id in self.varying
        if isinstance(e, ast.Call):
            chain = dotted_name(e.func)
            if chain == "len":
                return True
            return chain in _TIME_CALLS or any(
                chain.endswith("." + t) for t in _TIME_CALLS)
        if isinstance(e, ast.BinOp):
            return self._is_varying(e.left) or self._is_varying(e.right)
        if isinstance(e, ast.UnaryOp):
            return self._is_varying(e.operand)
        return False

    def _check_call(self, node: ast.Call, in_loop: bool) -> None:
        chain = dotted_name(node.func)
        if in_loop and _is_jit_entry(_wrap_chain(node)):
            self.hits.append((node.lineno,
                              f"{chain}(...) evaluated inside a loop body — a "
                              "fresh wrapper compiles every iteration; hoist "
                              "or memoize the wrapped program"))
            return
        if isinstance(node.func, ast.Name) and node.func.id in self.jitted:
            if self.jitted[node.func.id]:
                return  # static_argnums/static_argnames discipline declared
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if self._is_varying(arg):
                    src = ast.unparse(arg) if hasattr(ast, "unparse") else "?"
                    self.hits.append((node.lineno,
                                      f"per-call-varying Python scalar "
                                      f"`{src}` reaches jitted "
                                      f"{node.func.id}() — every new value "
                                      "retraces; pass it as a device scalar "
                                      "(jnp.int32/asarray), declare it in "
                                      "static_argnums/static_argnames, or "
                                      "bind it via a hashable partial"))

    def _taint_loop_target(self, t: ast.AST) -> None:
        if isinstance(t, ast.Name):
            self.varying.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._taint_loop_target(el)

    def _walk_expr(self, e: ast.AST, in_loop: bool) -> None:
        for node in ast.walk(e):
            if isinstance(node, ast.Call):
                self._check_call(node, in_loop)

    def scan(self, body: list[ast.stmt], depth: int = 0) -> None:
        for st in body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue  # nested scopes get their own _FnScan
            if isinstance(st, ast.For):
                self._walk_expr(st.iter, depth > 0)
                self._taint_loop_target(st.target)
                self.scan(st.body, depth + 1)
                self.scan(st.orelse, depth)
            elif isinstance(st, ast.While):
                self._walk_expr(st.test, depth > 0)
                self.scan(st.body, depth + 1)
                self.scan(st.orelse, depth)
            elif isinstance(st, ast.AugAssign):
                if depth > 0 and isinstance(st.target, ast.Name):
                    self.varying.add(st.target.id)  # loop counter
                self._walk_expr(st.value, depth > 0)
            else:
                for e in ast.iter_child_nodes(st):
                    if isinstance(e, (ast.expr, ast.withitem, ast.keyword)):
                        self._walk_expr(e, depth > 0)
                if isinstance(st, ast.If):
                    self.scan(st.body, depth)
                    self.scan(st.orelse, depth)
                elif isinstance(st, ast.With):
                    self.scan(st.body, depth)
                elif isinstance(st, ast.Try):
                    self.scan(st.body, depth)
                    for h in st.handlers:
                        self.scan(h.body, depth)
                    self.scan(st.orelse, depth)
                    self.scan(st.finalbody, depth)


class RecompileHazardRule(Rule):
    id = "GL011"
    title = "jit/pjit/scan callsite recompiles on every call"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        findings: list[Finding] = []
        seen: set[tuple[str, int, str]] = set()

        def emit(fn_name: str, hits: list[tuple[int, str]]) -> None:
            for line, what in hits:
                key = (fn_name, line, what)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(Finding(
                    self.id, mod.relpath, line,
                    f"{what} (in {fn_name!r})",
                    symbol=f"{fn_name}:L{line}"))

        def walk_scope(body: list[ast.stmt], inherited: dict[str, bool],
                       owner: str) -> None:
            jn = _JittedNames()
            jn.harvest(body)
            scope = dict(inherited, **jn.names)
            scan = _FnScan(self, mod, scope, owner)
            scan.scan(body)
            emit(owner, scan.hits)
            for st in body:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    walk_scope(st.body, scope, st.name)
                elif isinstance(st, ast.ClassDef):
                    for sub in st.body:
                        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                            walk_scope(sub.body, scope, f"{st.name}.{sub.name}")

        walk_scope(mod.tree.body, {}, "<module>")
        return findings
