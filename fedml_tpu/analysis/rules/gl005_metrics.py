"""GL005 — metric-namespace: registry families match ``fedml_[a-z0-9_]+``.

The static half of ``tests/test_metric_lint.py`` (which imports every
instrumented module and asserts over the live registry — it now delegates
its name/label validation here): every ``REGISTRY.counter/gauge/histogram``
call with a literal family name must carry the ``fedml_`` namespace, label
names must be valid Prometheus label identifiers, and ``le`` is reserved
for histogram buckets.  Catching it in lint means a bad family name fails
before anything imports, including in modules no test exercises yet.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from ..engine import Finding, ModuleInfo, Rule, dotted_name, str_const

METRIC_NAME_RE = re.compile(r"fedml_[a-z0-9_]+")
LABEL_RE = re.compile(r"[a-z][a-z0-9_]*")
_FACTORIES = ("counter", "gauge", "histogram")


def _is_registry_call(call: ast.Call) -> bool:
    chain = dotted_name(call.func)
    if "." not in chain:
        return False
    recv, tail = chain.rsplit(".", 1)
    return tail in _FACTORIES and recv.rsplit(".", 1)[-1] == "REGISTRY"


class MetricNamespaceRule(Rule):
    id = "GL005"
    title = "global-registry metric families must be fedml_-namespaced"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call) and _is_registry_call(node)):
                continue
            name = str_const(node.args[0]) if node.args else None
            if name is None:
                findings.append(Finding(
                    self.id, mod.relpath, node.lineno,
                    "metric family registered with a non-literal name — GL005 "
                    "cannot verify the fedml_ namespace",
                    symbol=f"nonliteral:L{node.lineno}"))
                continue
            if not METRIC_NAME_RE.fullmatch(name):
                findings.append(Finding(
                    self.id, mod.relpath, node.lineno,
                    f"metric family {name!r} violates the fedml_[a-z0-9_]+ "
                    "namespace",
                    symbol=name))
            for kw in node.keywords:
                if kw.arg != "labels":
                    continue
                if not isinstance(kw.value, (ast.Tuple, ast.List)):
                    continue  # non-literal labels: runtime lint still covers it
                for elt in kw.value.elts:
                    label = str_const(elt)
                    if label is None:
                        continue
                    if not LABEL_RE.fullmatch(label):
                        findings.append(Finding(
                            self.id, mod.relpath, node.lineno,
                            f"metric {name!r} label {label!r} is not a valid "
                            "label name ([a-z][a-z0-9_]*)",
                            symbol=f"{name}:{label}"))
                    elif label == "le":
                        findings.append(Finding(
                            self.id, mod.relpath, node.lineno,
                            f"metric {name!r} label 'le' is reserved for "
                            "histogram buckets",
                            symbol=f"{name}:le"))
        return findings
