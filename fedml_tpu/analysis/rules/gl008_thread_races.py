"""GL008 — thread-shared-state races: every mutable attr shared across
thread roots needs one common lock.

GL004 catches the narrow shape "attr written under ``with self._lock`` in
one method, touched bare in another".  This rule generalizes to the actual
failure condition: an instance attribute reachable from **two or more
thread roots**, **written** outside construction, with **no single lock
held at every access**.  Thread roots are discovered, not assumed:

- ``threading.Thread(target=self.m)`` / ``threading.Timer(dt, self.m)`` /
  ``executor.submit(self.m)`` — ``m`` runs on its own thread;
- methods registered as comm handlers
  (``register_message_receive_handler(T, self.m)`` anywhere in the
  package — name-matched so a subclass overriding a handler the base
  class registered is still rooted) — ``m`` runs on the receive loop;
- local closures handed to any of the above or to
  ``add_comm_event_sink`` become their own synthetic root (only the
  closure's accesses run on the foreign thread, not the whole method);
- every public method is collectively the *caller* root — the user's
  thread.

Reachability follows ``self.<m>()`` calls transitively, and lock context
is inferred interprocedurally: a method whose every internal call site
holds ``self._lock`` analyzes as entered with it held (fixpoint), so the
``# graftlint: disable=GL004(caller holds ...)`` helpers do not re-fire
here — only genuinely barred accesses do.  Exemptions that keep this
quiet on safe code: ctor accesses (no concurrency exists yet), attrs
never written outside the ctor (immutable config), locks themselves, and
attrs touched from a single root (thread-confined state).
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from ..engine import Finding, ModuleInfo, Rule
from ._concurrency import (
    class_locks, display_lock, module_locks, scan_function, sync_object_attrs,
)

_CTOR = {"__init__", "__new__"}

#: entry-lock lattice TOP — "no call site seen yet"
_TOP = None


class _ClassInfo:
    def __init__(self, relpath: str, name: str):
        self.relpath = relpath
        self.name = name
        self.locks: dict[str, str] = {}
        #: attrs holding internally-synchronized objects (Event/Queue/deque):
        #: method calls on them are safe; only rebinding races
        self.sync_attrs: set[str] = set()
        #: method name -> FunctionScan
        self.scans: dict = {}
        #: method name -> def line
        self.lines: dict[str, int] = {}
        #: method names registered as thread/timer/submit targets in-class
        self.thread_methods: set[str] = set()
        #: self-methods registered as comm event sinks (run on the receive loop)
        self.sink_methods: set[str] = set()
        #: (method, localdef-name) closures handed to a thread/callback
        self.closure_roots: set[tuple[str, str]] = set()


class ThreadRaceRule(Rule):
    id = "GL008"
    title = "attr shared across thread roots without a common lock"

    def __init__(self):
        self._classes: list[_ClassInfo] = []
        #: method names registered as comm handlers anywhere in the package
        self._handler_names: set[str] = set()

    # -- phase 1: per-module collection --------------------------------------
    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        mlocks = module_locks(mod.tree)
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            info = _ClassInfo(mod.relpath, cls.name)
            info.locks = class_locks(cls)
            info.sync_attrs = sync_object_attrs(cls)
            for m in cls.body:
                if not isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                scan = scan_function(m, info.locks, mlocks, mod.relpath, cls.name)
                info.scans[m.name] = scan
                info.lines[m.name] = m.lineno
                for t in scan.thread_targets:
                    if t.kind == "handler" and t.method:
                        self._handler_names.add(t.method)
                    if t.method:
                        if t.kind in ("thread", "timer", "submit"):
                            info.thread_methods.add(t.method)
                        elif t.kind == "sink":
                            info.sink_methods.add(t.method)
                    elif t.localdef:
                        info.closure_roots.add((m.name, t.localdef))
            self._classes.append(info)
        return ()

    # -- phase 2: per-class race analysis ------------------------------------
    def finalize(self, modules) -> Iterable[Finding]:
        findings: list[Finding] = []
        for info in self._classes:
            findings.extend(self._check_class(info))
        return findings

    def _roots(self, info: _ClassInfo) -> dict[str, set[str]]:
        """root id -> seed method names.  Closure roots are handled apart.

        Every registered handler of one manager runs on the SAME receive
        loop (the comm manager dispatches sequentially), so all handler
        methods share one ``receive-loop`` root — two handlers touching the
        same attr is not, by itself, concurrency."""
        roots: dict[str, set[str]] = {}
        for m in info.scans:
            if m in info.thread_methods:
                roots[f"thread:{m}"] = {m}
            elif m in self._handler_names or m in info.sink_methods:
                roots.setdefault("receive-loop", set()).add(m)
        seeded = {m for ms in roots.values() for m in ms}
        caller = {m for m in info.scans
                  if not m.startswith("_") and m not in seeded}
        if caller:
            roots["caller"] = caller
        return roots

    def _reach(self, info: _ClassInfo, seeds: set[str]) -> set[str]:
        out, frontier = set(seeds), list(seeds)
        while frontier:
            m = frontier.pop()
            scan = info.scans.get(m)
            if scan is None:
                continue
            for call in scan.self_calls:
                if call.name in info.scans and call.name not in out:
                    out.add(call.name)
                    frontier.append(call.name)
        return out

    def _entry_locks(self, info: _ClassInfo, rooted: set[str]) -> dict[str, frozenset]:
        """Fixpoint: the set of locks PROVABLY held on every entry to each
        method.  Root/public methods enter bare; an internal helper's entry
        set is the intersection over its call sites of (locks held at the
        site plus the caller's own entry set)."""
        entry: dict[str, Optional[frozenset]] = {
            m: (frozenset() if (m in rooted or not m.startswith("_") or m in _CTOR)
                else _TOP)
            for m in info.scans
        }
        for _ in range(len(info.scans) + 2):
            changed = False
            for caller, scan in info.scans.items():
                base = entry[caller]
                if base is _TOP:
                    continue
                for call in scan.self_calls:
                    if call.name not in entry:
                        continue
                    contrib = frozenset(call.held) | base
                    cur = entry[call.name]
                    new = contrib if cur is _TOP else (cur & contrib)
                    if new != cur:
                        entry[call.name] = new
                        changed = True
            if not changed:
                break
        return {m: (s if s is not _TOP else frozenset()) for m, s in entry.items()}

    def _check_class(self, info: _ClassInfo) -> list[Finding]:
        roots = self._roots(info)
        has_foreign = any(r != "caller" for r in roots) or info.closure_roots
        if not has_foreign:
            return []  # nothing concurrent ever starts from this class
        rooted_seeds = {m for ms in roots.values() for m in ms}
        entry = self._entry_locks(info, rooted_seeds)
        # accesses per attr: (root, method, line, write, locks)
        per_attr: dict[str, list[tuple[str, str, int, bool, frozenset]]] = {}

        def add(root_id: str, method: str, acc) -> None:
            if acc.attr in info.sync_attrs and acc.mutcall:
                return  # mutating a synchronized object is safe; rebinds race
            locks = acc.held | entry.get(method, frozenset())
            per_attr.setdefault(acc.attr, []).append(
                (root_id, method, acc.line, acc.write, locks))

        for root_id, seeds in roots.items():
            for m in self._reach(info, seeds):
                if m in _CTOR:
                    continue
                for acc in info.scans[m].accesses:
                    # closure bodies belong to their own (possibly foreign)
                    # root, not the method that defines them
                    if acc.localdef is not None and (m, acc.localdef) in info.closure_roots:
                        continue
                    add(root_id, m, acc)
        for (method, local) in info.closure_roots:
            scan = info.scans.get(method)
            if scan is None:
                continue
            for acc in scan.accesses:
                if acc.localdef == local:
                    add(f"callback:{method}.{local}", method, acc)

        findings: list[Finding] = []
        for attr, accs in sorted(per_attr.items()):
            if attr in info.locks:
                continue
            roots_seen = {a[0] for a in accs}
            if len(roots_seen) < 2:
                continue
            if not any(write for _r, _m, _l, write, _k in accs):
                continue  # read-only outside the ctor: immutable after publish
            common = None
            for _r, _m, _l, _w, locks in accs:
                common = locks if common is None else (common & locks)
            if common:
                continue  # one lock covers every access
            # anchor at the first bare write if any, else the first bare access
            candidate_locks: set[str] = set()
            for _r, _m, _l, _w, locks in accs:
                candidate_locks |= locks
            bare = [a for a in accs if not a[4]] or accs
            bare_writes = [a for a in bare if a[3]]
            root_id, method, line, _w, _k = min(
                bare_writes or bare, key=lambda a: a[2])
            other_roots = sorted(roots_seen - {root_id}) or sorted(roots_seen)
            lock_hint = (f" (other sites hold {', '.join(sorted(display_lock(x) for x in candidate_locks))})"
                         if candidate_locks else "")
            findings.append(Finding(
                self.id, info.relpath, line,
                f"{info.name}.{attr} is shared with thread root(s) "
                f"{', '.join(other_roots)} but this access in {method}() "
                f"holds no common lock{lock_hint} — guard every access with "
                "one lock or document the single-writer invariant with a "
                "GL008 suppression",
                symbol=f"{info.name}.{attr}"))
        return findings
