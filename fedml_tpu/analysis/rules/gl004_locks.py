"""GL004 — lock-discipline: guarded attributes stay guarded.

For every class that owns a ``threading.Lock``/``RLock`` (an attribute
assigned ``threading.Lock()`` anywhere in the class), the rule computes the
set of instance attributes WRITTEN inside ``with self.<lock>:`` blocks — the
class's own declaration of what the lock protects — and then flags any
read or write of those attributes outside a lock-held region in any other
method.  The threaded comm managers and ``FedMLServerManager._agg_lock``
are the motivating targets: the receive-loop thread, the straggler
``threading.Timer``, and the caller's thread all touch round state.

Conventions the rule understands:

- ``__init__``/``__new__`` are construction — no concurrent access exists
  yet, so unguarded writes there are fine (they typically CREATE the
  guarded state);
- a method that runs entirely with the lock held by its caller carries one
  ``# graftlint: disable=GL004(caller holds <lock>)`` on its ``def`` line —
  the suppression IS the documentation of that invariant;
- nested functions defined inside a ``with self._lock:`` block count as
  lock-held (they run under the caller's critical section only if called
  there, which is the dominant pattern; escaping closures deserve the
  finding anyway).
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..engine import Finding, ModuleInfo, Rule, dotted_name

_CTOR_METHODS = {"__init__", "__new__"}


def _lock_attrs(cls: ast.ClassDef) -> set[str]:
    """self.<X> assigned threading.Lock()/RLock() anywhere in the class."""
    out: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            chain = dotted_name(node.value.func)
            if chain.rsplit(".", 1)[-1] in ("Lock", "RLock"):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        out.add(t.attr)
    return out


def _is_lock_withitem(item: ast.withitem, locks: set[str]) -> bool:
    ctx = item.context_expr
    if isinstance(ctx, ast.Attribute) and isinstance(ctx.value, ast.Name) \
            and ctx.value.id == "self" and ctx.attr in locks:
        return True
    # self._lock.acquire_timeout()-style helpers: treat any with on the lock
    # attribute's methods as holding it
    if isinstance(ctx, ast.Call) and isinstance(ctx.func, ast.Attribute):
        inner = ctx.func.value
        if isinstance(inner, ast.Attribute) and isinstance(inner.value, ast.Name) \
                and inner.value.id == "self" and inner.attr in locks:
            return True
    return False


def _self_attr(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return ""


class _MethodAccesses(ast.NodeVisitor):
    """(attr, line, is_write, lock_held) for every self.<attr> access."""

    def __init__(self, locks: set[str]):
        self.locks = locks
        self.held = 0
        self.accesses: list[tuple[str, int, bool, bool]] = []

    def visit_With(self, node: ast.With) -> None:
        lock_items = sum(1 for item in node.items if _is_lock_withitem(item, self.locks))
        for item in node.items:
            self.visit(item.context_expr)
        self.held += lock_items
        for stmt in node.body:
            self.visit(stmt)
        self.held -= lock_items

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr and attr not in self.locks:
            self.accesses.append(
                (attr, node.lineno, isinstance(node.ctx, (ast.Store, ast.Del)),
                 self.held > 0))
        self.generic_visit(node)


class LockDisciplineRule(Rule):
    id = "GL004"
    title = "attribute guarded by a lock in one method, accessed bare elsewhere"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        findings: list[Finding] = []
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            locks = _lock_attrs(cls)
            if not locks:
                continue
            methods = [n for n in cls.body
                       if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
            per_method: dict[str, list[tuple[str, int, bool, bool]]] = {}
            guarded: set[str] = set()
            guarded_in: dict[str, str] = {}
            for m in methods:
                v = _MethodAccesses(locks)
                for stmt in m.body:
                    v.visit(stmt)
                per_method[m.name] = v.accesses
                for attr, _line, is_write, held in v.accesses:
                    if held and is_write:
                        guarded.add(attr)
                        guarded_in.setdefault(attr, m.name)
            if not guarded:
                continue
            for m in methods:
                if m.name in _CTOR_METHODS:
                    continue
                for attr, line, is_write, held in per_method[m.name]:
                    if attr in guarded and not held:
                        verb = "written" if is_write else "read"
                        findings.append(Finding(
                            self.id, mod.relpath, line,
                            f"{cls.name}.{attr} is written under the lock in "
                            f"{guarded_in[attr]}() but {verb} here without it — "
                            "take the lock or document the single-writer "
                            "invariant with a GL004 suppression",
                            symbol=f"{cls.name}.{attr}:L{line}"))
        return findings
