"""GL007 — lock-order: acquisition cycles and blocking work under a lock.

The buffered-async server, the comm backends, the health ledger, and the
telemetry shippers together hold ~34 ``threading.Lock`` sites that the
receive loop, watchdog timers, and caller threads traverse concurrently.
Two whole-package invariants keep that surface deadlock-free:

1. **Lock acquisition order is acyclic.**  The rule builds the package's
   lock-acquisition graph: an edge ``A -> B`` whenever ``B`` is taken while
   ``A`` is held — directly (nested ``with``) or one call-hop away through
   a ``self.<method>()`` whose body takes ``B``.  A cycle means two threads
   can take the same pair in opposite orders and deadlock; a self-edge on a
   non-reentrant ``Lock`` (method holding it calls a method that re-takes
   it) deadlocks the very first time that path runs.
2. **No blocking operation runs under a lock.**  Socket send/recv/accept,
   ``time.sleep``, ``subprocess.*``, unbounded ``.join()``/``.wait()``,
   blocking queue reads, and jax host syncs (``.block_until_ready()``,
   ``jax.device_get``) executed while a lock is held turn one slow peer
   into a stalled critical section for every other thread — the 30-minute
   soak hang the runtime sanitizer exists to catch, caught at lint time.
   A deliberate hold (e.g. a per-socket write lock that exists precisely
   to serialize ``sendall``) carries a GL007 suppression naming that
   invariant.

Lock identities are module+class scoped, so cycle detection cannot alias
same-named locks of unrelated classes.  One-hop resolution covers
``self``-method calls AND cross-object attr calls: ``self.<attr>.<m>()``
while holding a lock resolves ``<attr>`` through the owning class's
``self.<attr> = SomeClass(...)`` assignments to SomeClass (cross-module,
fluent builders included) and projects the locks ``SomeClass.<m>`` acquires
as held-lock -> callee-lock edges — the manager-lock -> ledger-lock class
of ordering that used to be visible only to the runtime sanitizer.  Deeper
chains (two objects away) remain the sanitizer's half of the contract.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from ..engine import Finding, ModuleInfo, Rule
from ._concurrency import (
    class_attr_types, class_locks, display_lock, module_locks, scan_function,
)


class _FnInfo:
    __slots__ = ("name", "scan", "line")

    def __init__(self, name, scan, line):
        self.name = name
        self.scan = scan
        self.line = line


class LockOrderRule(Rule):
    id = "GL007"
    title = "lock-acquisition cycle or blocking operation under a lock"

    def __init__(self):
        #: (src, dst) -> (relpath, line, via) — first site observed
        self._edges: dict[tuple[str, str], tuple[str, int, str]] = {}
        self._kinds: dict[str, str] = {}
        #: class name -> method -> [lock ids acquired anywhere in the body]
        #: (same-named classes in different modules merge conservatively —
        #: a spurious union edge can only over-report, never miss a cycle)
        self._class_acquires: dict[str, dict[str, list[str]]] = {}
        #: deferred cross-object call sites, resolved in finalize once every
        #: class's locks are known: (relpath, line, qualname, attr, method,
        #: held, owner-class attr-type map)
        self._attr_call_sites: list[tuple] = []

    # -- per module ----------------------------------------------------------
    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        findings: list[Finding] = []
        mlocks = module_locks(mod.tree)
        for name, kind in mlocks.items():
            self._kinds[f"{mod.relpath}::{name}"] = kind
        # module-level functions: locks can only be the module-level ones
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan = scan_function(node, {}, mlocks, mod.relpath, None)
                self._collect(mod, scan, {}, node.name, findings)
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            locks = class_locks(cls)
            for attr, kind in locks.items():
                self._kinds[f"{mod.relpath}::{cls.name}.{attr}"] = kind
            attr_types = class_attr_types(cls)
            methods = [n for n in cls.body
                       if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
            scans = {m.name: _FnInfo(m.name, scan_function(
                m, locks, mlocks, mod.relpath, cls.name), m.lineno)
                for m in methods}
            acq_by_method = self._class_acquires.setdefault(cls.name, {})
            for info in scans.values():
                acq_by_method.setdefault(info.name, []).extend(
                    a.lock for a in info.scan.acquires)
                qualname = f"{cls.name}.{info.name}"
                self._collect(mod, info.scan, scans, qualname, findings)
                # cross-object one-hop: self.<attr>.<m>() while holding a
                # lock — resolution deferred to finalize (the callee class
                # may live in a module not yet parsed)
                for call in info.scan.attr_calls:
                    if call.held:
                        self._attr_call_sites.append(
                            (mod.relpath, call.line, qualname, call.attr,
                             call.method, call.held, attr_types))
        return findings

    def _collect(self, mod: ModuleInfo, scan, peer_scans: dict,
                 qualname: str, findings: list) -> None:
        # direct acquisition edges
        for acq in scan.acquires:
            for held in acq.held:
                if held != acq.lock:
                    self._edges.setdefault(
                        (held, acq.lock), (mod.relpath, acq.line, qualname))
                else:
                    self._self_edge(mod, acq.lock, acq.line, qualname, findings,
                                    via=None)
        # direct blocking ops
        for b in scan.blocking:
            if b.held:
                findings.append(self._blocking_finding(
                    mod, b.desc, b.line, qualname, b.held, via=None))
        # one hop: self.m() while holding locks — m's acquisitions/blocking
        # ops run under them too
        for call in scan.self_calls:
            if not call.held:
                continue
            callee = peer_scans.get(call.name)
            if callee is None:
                continue
            for acq in callee.scan.acquires:
                for held in call.held:
                    if held != acq.lock:
                        self._edges.setdefault(
                            (held, acq.lock),
                            (mod.relpath, call.line, f"{qualname} -> {call.name}()"))
                    else:
                        self._self_edge(mod, acq.lock, call.line, qualname,
                                        findings, via=call.name)
            for b in callee.scan.blocking:
                findings.append(self._blocking_finding(
                    mod, b.desc, call.line, qualname, call.held, via=call.name))

    def _self_edge(self, mod: ModuleInfo, lock: str, line: int, qualname: str,
                   findings: list, via: Optional[str]) -> None:
        if self._kinds.get(lock) == "RLock":
            return  # reentrant by design
        hop = f" via self.{via}()" if via else ""
        findings.append(Finding(
            self.id, mod.relpath, line,
            f"{qualname} re-acquires non-reentrant lock "
            f"{display_lock(lock)} while already holding it{hop} — this "
            "deadlocks on first execution",
            symbol=f"selfdeadlock:{qualname}:{display_lock(lock)}"))

    def _blocking_finding(self, mod: ModuleInfo, desc: str, line: int,
                          qualname: str, held, via: Optional[str]) -> Finding:
        hop = f" via self.{via}()" if via else ""
        locks = ", ".join(sorted(display_lock(h) for h in held))
        return Finding(
            self.id, mod.relpath, line,
            f"blocking {desc}{hop} while holding {locks} — every other "
            "thread entering this critical section stalls behind the "
            "slow peer; move it outside the lock or suppress naming the "
            "serialization invariant",
            symbol=f"block:{qualname}:{desc}")

    # -- cross-module: cycle detection ---------------------------------------
    def finalize(self, modules) -> Iterable[Finding]:
        # resolve the deferred cross-object hops now that every class's lock
        # acquisitions are known: held lock -> each lock the callee method
        # takes (one object hop only; aliased class names merge, which can
        # only add edges)
        for relpath, line, qualname, attr, method, held, attr_types in self._attr_call_sites:
            cls_name = attr_types.get(attr)
            if cls_name is None:
                continue
            for callee_lock in self._class_acquires.get(cls_name, {}).get(method, []):
                for h in held:
                    if h != callee_lock:
                        self._edges.setdefault(
                            (h, callee_lock),
                            (relpath, line, f"{qualname} -> {attr}.{method}()"))
        adj: dict[str, set[str]] = {}
        for (src, dst) in self._edges:
            adj.setdefault(src, set()).add(dst)
            adj.setdefault(dst, set())
        findings = []
        for scc in _sccs(adj):
            if len(scc) < 2:
                continue
            cyc = sorted(scc)
            # anchor at the first recorded edge inside the cycle
            anchor = min(
                (site for pair, site in self._edges.items()
                 if pair[0] in scc and pair[1] in scc),
                key=lambda s: (s[0], s[1]))
            path, line, via = anchor
            order = " -> ".join(display_lock(x) for x in cyc)
            findings.append(Finding(
                self.id, path, line,
                f"lock-order cycle {order} (edge recorded in {via}): two "
                "threads taking these locks in opposite orders deadlock — "
                "impose one global order or collapse to a single lock",
                symbol="cycle:" + "|".join(display_lock(x) for x in cyc)))
        return findings


def _sccs(adj: dict[str, set[str]]) -> list[list[str]]:
    """Tarjan (iterative) — strongly connected components of the lock graph."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    out: list[list[str]] = []
    counter = [0]

    for root in adj:
        if root in index:
            continue
        work = [(root, iter(sorted(adj[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(adj[nxt]))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                out.append(comp)
    return out
