"""Rule plugins for ``fedml-tpu lint`` — one module per rule.

Adding a rule: subclass :class:`fedml_tpu.analysis.engine.Rule`, give it the
next ``GLxxx`` id, and append the class to :data:`ALL_RULES`; the engine,
CLI, baseline, and suppression syntax pick it up with no further wiring.
"""

from .gl001_flags import FlagRegistryRule
from .gl002_jit_purity import JitPurityRule
from .gl003_donation import DonationSafetyRule
from .gl004_locks import LockDisciplineRule
from .gl005_metrics import MetricNamespaceRule
from .gl006_tracer_branch import TracerBranchRule
from .gl007_lock_order import LockOrderRule
from .gl008_thread_races import ThreadRaceRule
from .gl009_handlers import HandlerConformanceRule
from .gl010_host_sync import HostSyncRule
from .gl011_recompile import RecompileHazardRule
from .gl012_durability import AtomicDurabilityRule

ALL_RULES = [
    FlagRegistryRule,
    JitPurityRule,
    DonationSafetyRule,
    LockDisciplineRule,
    MetricNamespaceRule,
    TracerBranchRule,
    LockOrderRule,
    ThreadRaceRule,
    HandlerConformanceRule,
    HostSyncRule,
    RecompileHazardRule,
    AtomicDurabilityRule,
]

__all__ = ["ALL_RULES", "FlagRegistryRule", "JitPurityRule", "DonationSafetyRule",
           "LockDisciplineRule", "MetricNamespaceRule", "TracerBranchRule",
           "LockOrderRule", "ThreadRaceRule", "HandlerConformanceRule",
           "HostSyncRule", "RecompileHazardRule", "AtomicDurabilityRule"]
