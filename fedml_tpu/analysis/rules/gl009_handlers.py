"""GL009 — handler conformance: every sent message type has a receiver.

The comm managers raise ``KeyError`` at *runtime* when a message arrives
whose type no handler was registered for
(``FedMLCommManager.receive_message``) — in a threaded receive loop that
surfaces minutes into a soak as a contained-but-repeating handler error
and a silently stalled protocol.  This rule closes the loop statically,
package-wide (``finalize``):

- **unhandled send**: a ``Message(<TYPE>, ...)`` constructed anywhere with
  no ``register_message_receive_handler(<TYPE>, ...)`` in the whole
  package;
- **dead handler**: a registration for a type nothing ever sends — a
  protocol leftover that silently rots (reported at the registration).

Types resolve through ``MSG_TYPE_*`` constants (module-level int
assignments), dotted imports (``md.MSG_TYPE_S2C_FINISH``), literal ints,
and ``IfExp`` sends (both arms).  Sends whose type is a runtime value
(``Message(msg_type, ...)`` in a generic helper) are *wildcards*: they
cannot prove a handler missing, and any constant DEFINED in a module
containing a wildcard send is exempt from dead-handler reporting — that
module's protocol routes types we cannot see statically.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from ..engine import Finding, ModuleInfo, Rule, dotted_name

_CONST_PREFIX = "MSG_TYPE"


class _Site:
    __slots__ = ("relpath", "line", "idents", "label")

    def __init__(self, relpath: str, line: int, idents: frozenset, label: str):
        self.relpath = relpath
        self.line = line
        self.idents = idents  # symbolic constant names and/or int values
        self.label = label    # display form


class HandlerConformanceRule(Rule):
    id = "GL009"
    title = "message type sent without a registered handler (or dead handler)"

    def __init__(self):
        self._defs: dict[str, tuple[int, str]] = {}   # NAME -> (value, relpath)
        self._sends: list[_Site] = []
        self._registers: list[_Site] = []
        self._wildcard_modules: set[str] = set()

    # -- collection ----------------------------------------------------------
    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Constant) \
                    and isinstance(stmt.value.value, int):
                for t in stmt.targets:
                    if isinstance(t, ast.Name) and t.id.startswith(_CONST_PREFIX):
                        self._defs[t.id] = (stmt.value.value, mod.relpath)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = dotted_name(node.func)
            tail = fn.rsplit(".", 1)[-1]
            if tail == "Message" and node.args:
                idents, label = self._resolve(node.args[0])
                if idents:
                    self._sends.append(_Site(mod.relpath, node.lineno, idents, label))
                elif label == "<dynamic>":
                    self._wildcard_modules.add(mod.relpath)
            elif tail == "register_message_receive_handler" and node.args:
                idents, label = self._resolve(node.args[0])
                if idents:
                    self._registers.append(
                        _Site(mod.relpath, node.lineno, idents, label))
                # a dynamic registration wildcards nothing: it can only ADD
                # handlers, so missing-handler reporting stays sound, and
                # dead-handler reporting never fires on dynamic types anyway

        return ()

    def _resolve(self, node: ast.AST) -> tuple[frozenset, str]:
        """(identity set, display label).  Identities are constant NAMEs
        (resolved to values in finalize) or bare ints; an empty set with the
        '<dynamic>' label marks a wildcard send."""
        if isinstance(node, ast.Constant) and isinstance(node.value, int) \
                and not isinstance(node.value, bool):
            return frozenset([node.value]), str(node.value)
        name = dotted_name(node).rsplit(".", 1)[-1]
        if name.startswith(_CONST_PREFIX):
            return frozenset([name]), name
        if isinstance(node, ast.IfExp):
            a, la = self._resolve(node.body)
            b, lb = self._resolve(node.orelse)
            if a and b:
                return a | b, f"{la}|{lb}"
        return frozenset(), "<dynamic>"

    # -- matching ------------------------------------------------------------
    def _values(self, idents: frozenset) -> set:
        """Every comparable identity: the int values of resolvable names
        plus unresolvable names themselves (symbolic matching)."""
        out: set = set()
        for ident in idents:
            if isinstance(ident, int):
                out.add(ident)
            elif ident in self._defs:
                out.add(self._defs[ident][0])
            else:
                out.add(ident)
        return out

    def finalize(self, modules) -> Iterable[Finding]:
        sent: set = set()
        for s in self._sends:
            sent |= self._values(s.idents)
        handled: set = set()
        for r in self._registers:
            handled |= self._values(r.idents)
        findings: list[Finding] = []
        for s in self._sends:
            missing = self._values(s.idents) - handled
            if missing and len(missing) == len(self._values(s.idents)):
                findings.append(Finding(
                    self.id, s.relpath, s.line,
                    f"message type {s.label} is sent here but no "
                    "register_message_receive_handler for it exists anywhere "
                    "in the package — the receive loop will raise KeyError "
                    "and drop it",
                    symbol=f"unhandled:{s.label}"))
        for r in self._registers:
            if self._values(r.idents) & sent:
                continue
            # a constant owned by a module with dynamic sends may well be
            # routed through them — cannot call it dead
            owners = {self._defs[i][1] for i in r.idents
                      if not isinstance(i, int) and i in self._defs}
            owners.add(r.relpath)
            if owners & self._wildcard_modules:
                continue
            findings.append(Finding(
                self.id, r.relpath, r.line,
                f"handler registered for message type {r.label} but nothing "
                "in the package ever sends it — dead protocol surface "
                "(delete it or suppress naming the external sender)",
                symbol=f"dead:{r.label}"))
        return findings
