"""GL001 — flag-registry: every ``cfg.extra`` read declared, no dead flags.

Detected read idioms (all must name a flag declared in ``core/flags.py``):

- ``cfg_extra(cfg, "name"[, default])`` — the blessed accessor — plus its
  family: ``cfg_extra_present(cfg, "name")`` membership probes and
  ``set_cfg_extra(cfg, "name", value)`` writes (all registry-checked, all
  carrying the flag name at the second argument);
- ``extra.get("name", ...)`` / ``extra.setdefault("name", ...)`` /
  ``extra["name"]`` / ``"name" in extra`` where the receiver is extra-like
  (a ``cfg.extra`` attribute, a ``getattr(cfg, "extra", ...)`` expression,
  or a local assigned from one) — these legacy idioms additionally get a
  migrate-to-``cfg_extra`` finding so the accessor stays the ONE idiom;
- ``getattr(cfg, "name", default)`` duck-typed fallthrough reads, counted
  only when ``name`` is already declared (an undeclared duck-typed read is
  indistinguishable from a normal attribute — ``cfg_extra`` catches those
  at runtime instead).

Cross-module direction: a declaration with no read anywhere in the package
is dead and flagged at its line in ``core/flags.py``.  The registry is read
STATICALLY (the ``FlagSpec(...)`` calls in the flags module), so fixtures
can lint self-contained packages without importing anything.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional, Sequence

from ..engine import Finding, ModuleInfo, Rule, dotted_name, str_const

FLAGS_MODULE = "core/flags.py"

#: receivers whose ``.get``/subscript is an extra read even without tracking
#: an assignment (the near-universal local variable name)
_EXTRA_NAMES = {"extra"}


def _is_extra_expr(node: ast.AST, extra_vars: set[str]) -> bool:
    """Does this expression evaluate to a cfg.extra dict?"""
    if isinstance(node, ast.Name):
        return node.id in extra_vars or node.id in _EXTRA_NAMES
    if isinstance(node, ast.Attribute):
        return node.attr == "extra"
    if isinstance(node, ast.Call):
        fn = dotted_name(node.func)
        if fn == "getattr" and len(node.args) >= 2 and str_const(node.args[1]) == "extra":
            return True
        if fn == "dict" and node.args and _is_extra_expr(node.args[0], extra_vars):
            return True
        return False
    if isinstance(node, ast.BoolOp):  # (getattr(cfg, "extra", {}) or {})
        return any(_is_extra_expr(v, extra_vars) for v in node.values)
    return False


def declared_flags(flags_mod: ModuleInfo) -> dict[str, int]:
    """{flag name: declaration line} from the FlagSpec(...) calls."""
    out: dict[str, int] = {}
    for node in ast.walk(flags_mod.tree):
        if isinstance(node, ast.Call) and dotted_name(node.func).endswith("FlagSpec"):
            name = str_const(node.args[0]) if node.args else None
            if name is None:
                for kw in node.keywords:
                    if kw.arg == "name":
                        name = str_const(kw.value)
            if name is not None:
                out[name] = node.lineno
    return out


class _ReadSite:
    __slots__ = ("name", "line", "legacy", "duck")

    def __init__(self, name: Optional[str], line: int, legacy: bool, duck: bool = False):
        self.name = name      # None = non-literal flag name
        self.line = line
        self.legacy = legacy  # pre-cfg_extra idiom
        self.duck = duck      # getattr(cfg, "<flag>", ...) fallthrough


def _collect_reads(mod: ModuleInfo, declared: dict[str, int]) -> list[_ReadSite]:
    extra_vars: set[str] = set()
    reads: list[_ReadSite] = []
    for node in ast.walk(mod.tree):
        # track `extra = getattr(cfg, "extra", {}) or {}` style locals
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and _is_extra_expr(node.value, extra_vars):
            extra_vars.add(node.targets[0].id)
            continue
        if isinstance(node, ast.Call):
            fn = dotted_name(node.func)
            if fn.split(".")[-1] in ("cfg_extra", "cfg_extra_present",
                                     "set_cfg_extra") and len(node.args) >= 2:
                # the accessor family: value read, membership probe, and the
                # blessed write all take the flag name at args[1] and count
                # as registry-checked uses (keeps written-only flags alive)
                reads.append(_ReadSite(str_const(node.args[1]), node.lineno, legacy=False))
                continue
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("get", "setdefault") \
                    and node.args and _is_extra_expr(node.func.value, extra_vars):
                reads.append(_ReadSite(str_const(node.args[0]), node.lineno, legacy=True))
                continue
            if fn == "getattr" and len(node.args) >= 2:
                name = str_const(node.args[1])
                if name in declared:
                    reads.append(_ReadSite(name, node.lineno, legacy=False, duck=True))
                continue
        if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load) \
                and _is_extra_expr(node.value, extra_vars):
            reads.append(_ReadSite(str_const(node.slice), node.lineno, legacy=True))
            continue
        if isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                and _is_extra_expr(node.comparators[0], extra_vars):
            reads.append(_ReadSite(str_const(node.left), node.lineno, legacy=True))
    return reads


class FlagRegistryRule(Rule):
    id = "GL001"
    title = "cfg.extra flag reads must be declared in core/flags.py (and vice versa)"

    # whole-rule runs in finalize: the registry module can sort after its
    # readers, so per-module checking would race the declaration harvest
    def finalize(self, modules: Sequence[ModuleInfo]) -> Iterable[Finding]:
        flags_mod = next((m for m in modules if m.relpath.endswith(FLAGS_MODULE)), None)
        declared = declared_flags(flags_mod) if flags_mod is not None else {}
        used: set[str] = set()
        findings: list[Finding] = []
        for mod in modules:
            if mod.relpath.endswith(FLAGS_MODULE):
                continue  # the accessor's own extra.get is not a flag read site
            for site in _collect_reads(mod, declared):
                if site.name is None:
                    findings.append(Finding(
                        self.id, mod.relpath, site.line,
                        "extra flag read with a non-literal name — GL001 cannot "
                        "verify it against the registry; use a literal flag name",
                        symbol=f"nonliteral:L{site.line}"))
                    continue
                used.add(site.name)
                if not site.duck and site.name not in declared:
                    findings.append(Finding(
                        self.id, mod.relpath, site.line,
                        f"extra flag {site.name!r} is not declared in core/flags.py "
                        "(add a FlagSpec with type, default, and doc)",
                        symbol=f"undeclared:{site.name}"))
                if site.legacy:
                    findings.append(Finding(
                        self.id, mod.relpath, site.line,
                        f"legacy extra access for {site.name!r} — read it via "
                        "cfg_extra(cfg, name, default) from core/flags.py",
                        symbol=f"legacy:{site.name}"))
        if flags_mod is not None:
            findings += [
                Finding(self.id, flags_mod.relpath, line,
                        f"flag {name!r} is declared but never read anywhere in the "
                        "package — delete the declaration or wire the feature",
                        symbol=f"dead:{name}")
                for name, line in sorted(declared.items())
                if name not in used
            ]
        return findings
