"""GL012 — atomic-durability: every commit record under a durability
directory follows the tmp+fsync+``os.replace`` envelope.

Every recovery proof in the repo (server journal, client journal, model
publisher, AOT store, flight recorder, performance timeline) silently
depends on one filesystem invariant: a reader sees an OLD record or a
COMPLETE new one, never a torn write — and a record that survived
``os.replace`` actually reached the platter (the payload was fsync'd
before the rename).  A SIGKILL soak passing today does not prove the
envelope holds tomorrow; this rule pins it statically:

- **Direct writes under a durability directory** — ``open(path, 'w'/'a'/
  'x'/'+')`` (or ``Path.write_text``/``write_bytes``) where ``path`` is
  *dir-tainted* — is a finding: the envelope writes a ``tempfile.mkstemp``
  sibling and renames.  Deliberate append-only logs (whose recovery drops
  a torn tail) carry a suppression naming that invariant.
- **``os.replace`` without a payload fsync** — any ``os.replace`` in a
  function with no preceding ``os.fsync`` call: the rename orders
  metadata, not data; after a crash the new name can point at zero-length
  garbage.  This is unconditional (every ``os.replace`` in the package IS
  a durability commit).

**Dir taint** starts at the flag registry: literal ``*_dir`` flag names
read through ``cfg_extra`` (``aot_programs_dir``, ``server_journal_dir``,
``flight_dir``, ``timeline_dir``, ``model_publish_dir``, ...), plus
function parameters whose name ends in ``_dir`` or is ``directory``, and
``self.<attr>`` fields assigned from a tainted expression in ``__init__``.
It propagates through ``os.path.join/abspath/fspath``, ``str()``, and
``Path()``; ``tempfile.mkstemp(dir=tainted)`` results are NOT tainted —
the mkstemp sibling is exactly the envelope's tmp file.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional, Sequence

from ..engine import Finding, ModuleInfo, Rule, dotted_name, str_const
from .gl001_flags import FLAGS_MODULE, declared_flags

#: propagating path constructors: f(tainted, ...) stays tainted
_PATH_PROPAGATORS = {"os.path.join", "os.path.abspath", "os.path.realpath",
                     "os.fspath", "str", "Path", "pathlib.Path"}
_WRITE_MODES = ("w", "a", "x", "+")
_TAINT_PARAM_NAMES = {"directory"}


def _is_write_mode(call: ast.Call) -> bool:
    mode = None
    if len(call.args) >= 2:
        mode = str_const(call.args[1])
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = str_const(kw.value)
    return mode is not None and any(c in mode for c in _WRITE_MODES)


class _DirTaint:
    """Source-order dir-path taint for one function body."""

    def __init__(self, dir_flags: set[str], self_tainted: set[str]):
        self.dir_flags = dir_flags
        self.self_tainted = self_tainted  # tainted `self.<attr>` names
        self.tainted: set[str] = set()

    def expr(self, e: Optional[ast.AST]) -> bool:
        if e is None:
            return False
        if isinstance(e, ast.Name):
            return e.id in self.tainted
        if isinstance(e, ast.Attribute):
            if isinstance(e.value, ast.Name) and e.value.id == "self":
                return e.attr in self.self_tainted
            return self.expr(e.value)
        if isinstance(e, ast.Call):
            chain = dotted_name(e.func)
            if chain == "cfg_extra" and len(e.args) >= 2:
                name = str_const(e.args[1])
                return name is not None and (
                    name in self.dir_flags or name.endswith("_dir"))
            if chain in _PATH_PROPAGATORS or chain.endswith(".joinpath"):
                return any(self.expr(a) for a in e.args) or any(
                    self.expr(kw.value) for kw in e.keywords)
            if chain.startswith("tempfile."):
                return False  # the envelope's own tmp sibling
            if isinstance(e.func, ast.Attribute):
                # path methods on a tainted receiver (p / "x" is BinOp below)
                return self.expr(e.func.value)
            return False
        if isinstance(e, ast.BinOp):  # str concat / Path "/" operator
            return self.expr(e.left) or self.expr(e.right)
        if isinstance(e, ast.JoinedStr):
            return any(self.expr(v.value) for v in e.values
                       if isinstance(v, ast.FormattedValue))
        if isinstance(e, ast.Subscript):
            return self.expr(e.value)
        if isinstance(e, ast.IfExp):
            return self.expr(e.body) or self.expr(e.orelse)
        return False


def _class_self_taint(cls: ast.ClassDef, dir_flags: set[str]) -> set[str]:
    """``self.X`` attrs a ctor assigns from a dir-tainted expression."""
    out: set[str] = set()
    for node in cls.body:
        if not (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == "__init__"):
            continue
        taint = _DirTaint(dir_flags, set())
        for arg in node.args.args + node.args.kwonlyargs:
            if arg.arg.endswith("_dir") or arg.arg in _TAINT_PARAM_NAMES:
                taint.tainted.add(arg.arg)
        for st in ast.walk(node):
            if isinstance(st, ast.Assign) and taint.expr(st.value):
                for t in st.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        out.add(t.attr)
            elif isinstance(st, ast.Assign):
                for t in st.targets:
                    if isinstance(t, ast.Name) and taint.expr(st.value):
                        taint.tainted.add(t.id)
    return out


class AtomicDurabilityRule(Rule):
    id = "GL012"
    title = "non-atomic write under a durability directory / os.replace without fsync"

    def finalize(self, modules: Sequence[ModuleInfo]) -> Iterable[Finding]:
        flags_mod = next(
            (m for m in modules if m.relpath.endswith(FLAGS_MODULE)), None)
        dir_flags = set()
        if flags_mod is not None:
            dir_flags = {n for n in declared_flags(flags_mod)
                         if n.endswith("_dir")}
        findings: list[Finding] = []
        for mod in modules:
            if mod.relpath.endswith(FLAGS_MODULE):
                continue
            findings.extend(self._check(mod, dir_flags))
        return findings

    # ------------------------------------------------------------------
    def _check(self, mod: ModuleInfo, dir_flags: set[str]) -> list[Finding]:
        findings: list[Finding] = []

        def scan_fn(fn: ast.FunctionDef, qual: str, self_taint: set[str]) -> None:
            taint = _DirTaint(dir_flags, self_taint)
            for arg in fn.args.args + fn.args.kwonlyargs:
                if arg.arg.endswith("_dir") or arg.arg in _TAINT_PARAM_NAMES:
                    taint.tainted.add(arg.arg)
            fsync_lines: list[int] = []
            sites: list[tuple[int, str]] = []
            for node in ast.walk(fn):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and node is not fn:
                    continue
                if isinstance(node, ast.Assign) and taint.expr(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            taint.tainted.add(t.id)
                if not isinstance(node, ast.Call):
                    continue
                chain = dotted_name(node.func)
                if chain == "os.fsync" or chain.endswith(".fsync"):
                    fsync_lines.append(node.lineno)
                elif chain == "os.replace" or chain == "os.rename":
                    sites.append((node.lineno, "replace"))
                elif chain in ("open", "io.open") and node.args \
                        and taint.expr(node.args[0]) and _is_write_mode(node):
                    sites.append((node.lineno, "open"))
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("write_text", "write_bytes") \
                        and taint.expr(node.func.value):
                    sites.append((node.lineno, "open"))
            for line, kind in sites:
                if kind == "replace":
                    if not any(fl < line for fl in fsync_lines):
                        findings.append(Finding(
                            self.id, mod.relpath, line,
                            f"os.replace in {qual!r} with no preceding "
                            "os.fsync of the payload — the rename orders "
                            "metadata, not data; a crash can leave the new "
                            "name pointing at a torn record.  fsync the tmp "
                            "file before renaming",
                            symbol=f"{qual}:replace:L{line}"))
                else:
                    findings.append(Finding(
                        self.id, mod.relpath, line,
                        f"direct write under a durability directory in "
                        f"{qual!r} — readers can observe a torn record; use "
                        "the tmp+fsync+os.replace envelope (tempfile.mkstemp "
                        "sibling, os.fsync, os.replace).  Append-only logs "
                        "whose recovery tolerates a torn tail carry a "
                        "suppression naming that invariant",
                        symbol=f"{qual}:write:L{line}"))

        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan_fn(node, node.name, set())
            elif isinstance(node, ast.ClassDef):
                self_taint = _class_self_taint(node, dir_flags)
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        scan_fn(sub, f"{node.name}.{sub.name}", self_taint)
        return findings
