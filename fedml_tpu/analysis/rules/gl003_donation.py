"""GL003 — donation-safety: never read a variable after donating it.

``jax.jit(..., donate_argnums=...)`` hands the argument buffers to XLA for
in-place reuse: the caller's arrays are invalid afterwards, and on XLA:CPU
(jax 0.4.37) touching them corrupts the heap outright — the tier-1 suite's
historical wandering segfaults (``sim/engine.py``, ROADMAP).  The rule
tracks, per function scope:

1. names bound to ``jax.jit(fn, donate_argnums=<positions>)`` or
   ``jax.jit(fn, donate_argnames=<names>)`` (constant tuples/ints/strs,
   ``name = <const>`` indirection, and either arm of a conditional
   expression are resolved; argNAMES map to positions when the jitted
   callable is a lambda whose parameter list is visible);
2. calls through those names — positional args at donated positions and
   keyword args matching donated argnames become tainted at the call line;
   a ``*args`` splat covering a donated position taints the splatted
   sequence name itself (its elements were donated through it);
3. any later ``Load`` of a tainted name in the same scope is a finding,
   until an assignment rebinds it (the ``x = donating_fn(x)`` idiom is the
   correct pattern and stays clean).

Attribute targets remain out of static reach and are skipped — the rule is
deliberately precise-over-complete so every finding is actionable.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Iterable, Optional

from ..engine import Finding, ModuleInfo, Rule, dotted_name


def _const_positions(node: ast.AST, env: dict[str, ast.AST], depth: int = 0) -> Optional[set[int]]:
    """Evaluate a donate_argnums expression to a set of argument positions."""
    if depth > 4:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: set[int] = set()
        for elt in node.elts:
            got = _const_positions(elt, env, depth + 1)
            if got is None:
                return None
            out |= got
        return out
    if isinstance(node, ast.IfExp):  # e.g. () if cpu else (0, 1, 2)
        a = _const_positions(node.body, env, depth + 1) or set()
        b = _const_positions(node.orelse, env, depth + 1) or set()
        return a | b  # conservative union: donated on SOME path = donated
    if isinstance(node, ast.Name) and node.id in env:
        return _const_positions(env[node.id], env, depth + 1)
    return None


def _const_names(node: ast.AST, env: dict[str, ast.AST], depth: int = 0) -> set[str]:
    """Evaluate a donate_argnames expression to a set of parameter names."""
    if depth > 4:
        return set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: set[str] = set()
        for elt in node.elts:
            out |= _const_names(elt, env, depth + 1)
        return out
    if isinstance(node, ast.IfExp):
        return _const_names(node.body, env, depth + 1) | \
            _const_names(node.orelse, env, depth + 1)
    if isinstance(node, ast.Name) and node.id in env:
        return _const_names(env[node.id], env, depth + 1)
    return set()


def _callable_params(node: ast.AST) -> Optional[list[str]]:
    """Positional parameter names of an inline lambda target (the one form
    whose signature is visible at the jit() call itself)."""
    if isinstance(node, ast.Lambda):
        return [a.arg for a in node.args.args]
    return None


@dataclass(frozen=True)
class _Donation:
    """What a jitted name donates: argument positions and/or argnames."""

    positions: frozenset
    names: frozenset

    def __bool__(self) -> bool:
        return bool(self.positions) or bool(self.names)


def _jit_donations(call: ast.Call, env: dict[str, ast.AST]) -> Optional[_Donation]:
    """The donation set when ``call`` is a jax.jit/pjit with donate_arg*."""
    chain = dotted_name(call.func)
    if chain.rsplit(".", 1)[-1] not in ("jit", "pjit"):
        return None
    positions: set[int] = set()
    names: set[str] = set()
    seen = False
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            seen = True
            positions |= _const_positions(kw.value, env) or set()
        elif kw.arg == "donate_argnames":
            seen = True
            got = _const_names(kw.value, env)
            names |= got
            # map names to positions when the callable's signature is visible
            params = _callable_params(call.args[0]) if call.args else None
            if params is not None:
                positions |= {params.index(n) for n in got if n in params}
    return _Donation(frozenset(positions), frozenset(names)) if seen else None


class DonationSafetyRule(Rule):
    id = "GL003"
    title = "variable read after being donated to a jitted call"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        findings: list[Finding] = []

        def scan_scope(body: list[ast.stmt]) -> None:
            env: dict[str, ast.AST] = {}          # simple name -> last value expr
            donating: dict[str, _Donation] = {}   # jitted-fn name -> donations
            tainted: dict[str, int] = {}          # var -> donation line

            class ScopeVisitor(ast.NodeVisitor):
                def visit_FunctionDef(self, node):  # new scope: recurse separately
                    scan_scope(node.body)

                visit_AsyncFunctionDef = visit_FunctionDef

                def visit_ClassDef(self, node):
                    scan_scope(node.body)

                def visit_Lambda(self, node):
                    pass  # separate (expression) scope; nothing donated inside

                def visit_Assign(self, node):
                    self.visit(node.value)
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            env[t.id] = node.value
                            tainted.pop(t.id, None)  # rebinding un-taints
                            donated = (_jit_donations(node.value, env)
                                       if isinstance(node.value, ast.Call) else None)
                            if donated:
                                donating[t.id] = donated

                def visit_Call(self, node):
                    # direct jax.jit(f, donate_argnums=...)(a, b) application
                    donated: Optional[_Donation] = None
                    if isinstance(node.func, ast.Call):
                        donated = _jit_donations(node.func, env)
                    elif isinstance(node.func, ast.Name) and node.func.id in donating:
                        donated = donating[node.func.id]
                    if donated:
                        for pos, arg in enumerate(node.args):
                            if isinstance(arg, ast.Starred):
                                # the splat covers every remaining position:
                                # if any of them is donated, the splatted
                                # sequence's buffers went with the call
                                if isinstance(arg.value, ast.Name) and any(
                                        p >= pos for p in donated.positions):
                                    tainted.setdefault(arg.value.id, node.lineno)
                                break
                            if pos in donated.positions and isinstance(arg, ast.Name):
                                tainted.setdefault(arg.id, node.lineno)
                        # args themselves are reads AT the call — fine; visit
                        # keywords/func only so the donated args don't self-flag
                        for kw in node.keywords:
                            if kw.arg in donated.names and isinstance(kw.value, ast.Name):
                                tainted.setdefault(kw.value.id, node.lineno)
                            self.visit(kw.value)
                        return
                    self.generic_visit(node)

                def visit_Name(self, node):
                    if isinstance(node.ctx, ast.Load) and node.id in tainted \
                            and node.lineno > tainted[node.id]:
                        findings.append(Finding(
                            DonationSafetyRule.id, mod.relpath, node.lineno,
                            f"{node.id!r} was donated to a jitted call at line "
                            f"{tainted[node.id]} (donate_argnums) and read again "
                            "here — donated buffers are invalid after the call "
                            "(and corrupt the heap on XLA:CPU)",
                            symbol=f"{node.id}:L{node.lineno}"))
                    elif isinstance(node.ctx, ast.Store):
                        tainted.pop(node.id, None)

            v = ScopeVisitor()
            for stmt in body:
                v.visit(stmt)

        scan_scope(mod.tree.body)
        return findings
