"""Shared AST machinery for the concurrency rules (GL007/GL008).

Both rules reason about the same raw material — which locks a class owns,
which statements run with which locks held, which ``self.<attr>`` accesses
happen where, and which methods run on which thread — so the single
:class:`FunctionScan` walker here produces one event stream per function
and each rule projects out what it needs:

- GL007 (lock order) consumes the *acquisition* events (``with`` on a lock
  while other locks are held), the *self-call* events (one-hop
  interprocedural edges), and the *blocking-call* events;
- GL008 (thread races) consumes the *access* events (attr, write-kind,
  locks held) plus the *thread-root* registrations.

Lock identities are scoped to their defining module+class
(``relpath::Cls.attr`` / ``relpath::NAME`` for module-level locks) so
fixture packages and same-named classes in different modules never alias.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..engine import dotted_name

#: methods that mutate their receiver — a call of one of these on
#: ``self.<attr>`` counts as a WRITE of the attr for race purposes
MUTATOR_METHODS = {
    "append", "appendleft", "add", "extend", "update", "setdefault", "insert",
    "pop", "popleft", "popitem", "remove", "discard", "clear", "sort",
    "reverse", "put", "put_nowait",
}

_CTOR_METHODS = {"__init__", "__new__"}


# -- lock discovery -----------------------------------------------------------

def _lock_kind(value: ast.AST) -> Optional[str]:
    """'Lock' / 'RLock' when ``value`` is a ``threading.Lock()``-style call.
    A ``Condition`` is a lock too (``with self._cv:`` guards state exactly
    like a mutex) and is reentrant by default (wraps an RLock)."""
    if isinstance(value, ast.Call):
        tail = dotted_name(value.func).rsplit(".", 1)[-1]
        if tail in ("Lock", "RLock"):
            return tail
        if tail == "Condition":
            return "RLock"
    return None


def class_locks(cls: ast.ClassDef) -> dict[str, str]:
    """``{attr: kind}`` for ``self.<attr> = threading.Lock()`` assignments
    anywhere in the class plus class-level ``<attr> = threading.Lock()``."""
    out: dict[str, str] = {}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        kind = _lock_kind(node.value)
        if kind is None:
            continue
        for t in node.targets:
            if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                out[t.attr] = kind
            elif isinstance(t, ast.Name) and node in cls.body:
                out[t.id] = kind  # class-level shared lock
    return out


#: constructors whose instances are internally synchronized (or, for deque,
#: whose single-element ops are GIL-atomic in CPython) — method calls on an
#: attr holding one of these are not races; only REBINDING the attr is
_SYNC_OBJECT_CTORS = {
    "Event", "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue", "deque",
    "Condition", "Semaphore", "BoundedSemaphore", "Barrier",
}


def sync_object_attrs(cls: ast.ClassDef) -> set[str]:
    """Attrs assigned a thread-safe container/primitive anywhere in the
    class (``self.x = threading.Event()`` / ``queue.Queue()`` / ...)."""
    out: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            tail = dotted_name(node.value.func).rsplit(".", 1)[-1]
            if tail in _SYNC_OBJECT_CTORS:
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        out.add(t.attr)
    return out


def _ctor_class_name(value: ast.AST) -> Optional[str]:
    """Class name a constructor-ish assignment value refers to:
    ``Cls(...)``, ``pkg.mod.Cls(...)``, and the fluent-builder form
    ``Cls(...).attach(...)`` (a method chain whose root is a ctor call —
    the ledger's ``ClientHealthLedger().attach_comm()`` idiom)."""
    if not isinstance(value, ast.Call):
        return None
    func = value.func
    if isinstance(func, ast.Name):
        name = func.id
        return name if name[:1].isupper() else None
    if isinstance(func, ast.Attribute):
        if isinstance(func.value, ast.Call):
            return _ctor_class_name(func.value)  # fluent chain: recurse to root
        name = func.attr
        return name if name[:1].isupper() else None
    return None


def class_attr_types(cls: ast.ClassDef) -> dict[str, str]:
    """``{attr: ClassName}`` for ``self.<attr> = SomeClass(...)`` assignments
    anywhere in the class — the receiver-type map the GL007 cross-object
    one-hop resolution uses to find which locks ``self.<attr>.<m>()`` can
    take."""
    out: dict[str, str] = {}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        cname = _ctor_class_name(node.value)
        if cname is None:
            continue
        for t in node.targets:
            if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                out[t.attr] = cname
    return out


def module_locks(tree: ast.Module) -> dict[str, str]:
    """Module-level ``NAME = threading.Lock()`` assignments."""
    out: dict[str, str] = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            kind = _lock_kind(stmt.value)
            if kind:
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = kind
    return out


def lock_id(relpath: str, cls_name: Optional[str], name: str) -> str:
    return f"{relpath}::{cls_name}.{name}" if cls_name else f"{relpath}::{name}"


def display_lock(lid: str) -> str:
    """Human form of a lock id: strip the module prefix."""
    return lid.split("::", 1)[-1]


# -- blocking-call classification --------------------------------------------

#: attribute calls that block on I/O or another thread — held under a lock
#: they serialize every other critical-section entrant behind the peer
_BLOCKING_ATTRS = {"recv", "recvfrom", "recv_into", "accept", "sendall",
                   "connect", "block_until_ready"}


def classify_blocking(node: ast.Call) -> Optional[str]:
    """A short description when ``node`` is a blocking operation, else None.

    Recognized: ``time.sleep``, any ``subprocess.*`` call, socket
    send/recv/accept/connect, jax host syncs (``.block_until_ready()`` /
    ``jax.device_get``), blocking ``<queue>.get()`` with no timeout, and
    zero-arg ``.join()``/``.wait()`` (thread join / event wait, unbounded).
    """
    chain = dotted_name(node.func)
    if chain == "time.sleep":
        return "time.sleep()"
    if chain.startswith("subprocess."):
        return f"{chain}()"
    if chain == "jax.device_get":
        return "jax.device_get()"
    if isinstance(node.func, ast.Attribute):
        attr = node.func.attr
        if attr in _BLOCKING_ATTRS:
            return f".{attr}()"
        has_timeout = any(kw.arg in ("timeout", "block") for kw in node.keywords)
        if attr == "get" and not node.args and not node.keywords:
            # .get() with no key is a queue drain, not a dict lookup; only
            # queue-looking receivers count so dict.get(key) stays silent
            recv = dotted_name(node.func.value).rsplit(".", 1)[-1].lower()
            if any(h in recv for h in ("queue", "inbox", "mailbox")) or recv in ("q", "_q"):
                return ".get() (blocking queue read, no timeout)"
        if attr in ("join", "wait") and not node.args and not has_timeout:
            return f".{attr}() (unbounded)"
    return None


# -- the per-function walker --------------------------------------------------

class Access:
    __slots__ = ("attr", "line", "write", "held", "localdef", "mutcall")

    def __init__(self, attr: str, line: int, write: bool,
                 held: frozenset, localdef: Optional[str], mutcall: bool = False):
        self.attr = attr
        self.line = line
        self.write = write
        self.held = held          # lock ids held at the access
        self.localdef = localdef  # name of the enclosing nested def, if any
        self.mutcall = mutcall    # write via a mutator METHOD (not a rebind)


class SelfCall:
    __slots__ = ("name", "line", "held", "localdef")

    def __init__(self, name: str, line: int, held: frozenset, localdef):
        self.name = name
        self.line = line
        self.held = held
        self.localdef = localdef


class AttrMethodCall:
    """``self.<attr>.<method>(...)`` — a one-hop call INTO another object.
    GL007 resolves ``attr`` through the owning class's attr-type map and
    adds held-lock -> callee-lock edges (the manager-lock -> ledger-lock
    class of ordering that used to be runtime-sanitizer-only)."""

    __slots__ = ("attr", "method", "line", "held")

    def __init__(self, attr: str, method: str, line: int, held: frozenset):
        self.attr = attr
        self.method = method
        self.line = line
        self.held = held


class Acquire:
    __slots__ = ("lock", "line", "held")

    def __init__(self, lock: str, line: int, held: frozenset):
        self.lock = lock
        self.line = line
        self.held = held  # locks already held when this one is taken


class BlockingCall:
    __slots__ = ("desc", "line", "held")

    def __init__(self, desc: str, line: int, held: frozenset):
        self.desc = desc
        self.line = line
        self.held = held


class ThreadTarget:
    """A callable handed to another thread: Thread(target=...), Timer,
    executor.submit, a registered comm handler, or a comm event sink."""

    __slots__ = ("kind", "method", "localdef", "line")

    def __init__(self, kind: str, method: Optional[str], localdef: Optional[str], line: int):
        self.kind = kind          # "thread" | "timer" | "submit" | "handler" | "sink"
        self.method = method      # self.<method> target, if that form
        self.localdef = localdef  # local closure/lambda target, if that form
        self.line = line


class FunctionScan(ast.NodeVisitor):
    """One pass over a function body collecting the concurrency events.

    ``locks`` maps syntactic receivers to lock ids: ``self.<attr>`` for
    instance/class locks and bare names for module-level locks.  Nested
    function bodies are walked too (their code usually runs under the
    enclosing critical section, or on another thread — the ``localdef``
    tag lets GL008 reassign them to callback roots).
    """

    def __init__(self, self_locks: dict[str, str], mod_locks: dict[str, str],
                 relpath: str, cls_name: Optional[str]):
        self.self_locks = self_locks
        self.mod_locks = mod_locks
        self.relpath = relpath
        self.cls_name = cls_name
        self._held: list[str] = []
        self._localdef: list[str] = []
        self.accesses: list[Access] = []
        self.self_calls: list[SelfCall] = []
        self.attr_calls: list[AttrMethodCall] = []
        self.acquires: list[Acquire] = []
        self.blocking: list[BlockingCall] = []
        self.thread_targets: list[ThreadTarget] = []

    # -- helpers ------------------------------------------------------------
    def _lock_of(self, ctx: ast.AST) -> Optional[str]:
        """Lock id for a with-item context expression, else None."""
        if isinstance(ctx, ast.Call) and isinstance(ctx.func, ast.Attribute):
            # with self._lock.acquire_timeout()-style helpers hold the lock
            inner = self._lock_of(ctx.func.value)
            if inner:
                return inner
        if isinstance(ctx, ast.Attribute) and isinstance(ctx.value, ast.Name):
            if ctx.value.id == "self" and ctx.attr in self.self_locks:
                return lock_id(self.relpath, self.cls_name, ctx.attr)
            if ctx.value.id == self.cls_name and ctx.attr in self.self_locks:
                return lock_id(self.relpath, self.cls_name, ctx.attr)
        if isinstance(ctx, ast.Name) and ctx.id in self.mod_locks:
            return lock_id(self.relpath, None, ctx.id)
        return None

    def _snapshot(self) -> frozenset:
        return frozenset(self._held)

    def _self_attr(self, node: ast.AST) -> str:
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            return node.attr
        return ""

    def _cur_localdef(self) -> Optional[str]:
        return self._localdef[-1] if self._localdef else None

    def _record(self, attr: str, line: int, write: bool,
                mutcall: bool = False) -> None:
        if attr and attr not in self.self_locks:
            self.accesses.append(Access(attr, line, write, self._snapshot(),
                                        self._cur_localdef(), mutcall))

    # -- visitors -----------------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        taken: list[str] = []
        for item in node.items:
            self.visit(item.context_expr)
            lid = self._lock_of(item.context_expr)
            if lid is not None:
                self.acquires.append(Acquire(lid, node.lineno, self._snapshot()))
                self._held.append(lid)
                taken.append(lid)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        for _ in taken:
            self._held.pop()

    visit_AsyncWith = visit_With

    def visit_FunctionDef(self, node) -> None:
        self._localdef.append(node.name)
        self.generic_visit(node)
        self._localdef.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._localdef.append(f"<lambda:{node.lineno}>")
        self.generic_visit(node)
        self._localdef.pop()

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = self._self_attr(node)
        if attr:
            self._record(attr, node.lineno,
                         isinstance(node.ctx, (ast.Store, ast.Del)))
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # self.x[k] = v / del self.x[k] mutate the container: count as write
        attr = self._self_attr(node.value)
        if attr and isinstance(node.ctx, (ast.Store, ast.Del)):
            self._record(attr, node.lineno, True, mutcall=True)
            self.visit(node.slice)
            return
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        desc = classify_blocking(node)
        if desc is not None:
            self.blocking.append(BlockingCall(desc, node.lineno, self._snapshot()))
        # self.m(...) one-hop call edge
        if isinstance(node.func, ast.Attribute) and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self":
            self.self_calls.append(SelfCall(node.func.attr, node.lineno,
                                            self._snapshot(), self._cur_localdef()))
        elif isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Attribute) \
                and isinstance(node.func.value.value, ast.Name) \
                and node.func.value.value.id == "self":
            # self.<attr>.<method>(...): the cross-OBJECT one-hop call —
            # GL007 resolves <attr>'s class and projects its locks
            self.attr_calls.append(AttrMethodCall(
                node.func.value.attr, node.func.attr, node.lineno,
                self._snapshot()))
            # fall through to the mutator check below (self.x.append(...)
            # is both an attr-call and a write of x)
            if node.func.attr in MUTATOR_METHODS:
                self._record(node.func.value.attr, node.lineno, True, mutcall=True)
        else:
            # self.<attr>.mutator(...) is a write of <attr>
            if isinstance(node.func, ast.Attribute) and node.func.attr in MUTATOR_METHODS:
                attr = self._self_attr(node.func.value)
                if attr:
                    self._record(attr, node.lineno, True, mutcall=True)
        self._scan_thread_target(node)
        self.generic_visit(node)

    # -- thread-root registration sites -------------------------------------
    def _target_of(self, arg: ast.AST) -> tuple[Optional[str], Optional[str]]:
        """(self-method name, local-def name) a callable argument refers to."""
        if isinstance(arg, ast.Attribute) and isinstance(arg.value, ast.Name) \
                and arg.value.id == "self":
            return arg.attr, None
        if isinstance(arg, ast.Name):
            return None, arg.id
        if isinstance(arg, ast.Lambda):
            return None, f"<lambda:{arg.lineno}>"
        return None, None

    def _scan_thread_target(self, node: ast.Call) -> None:
        chain = dotted_name(node.func)
        tail = chain.rsplit(".", 1)[-1]
        if tail in ("Thread", "Timer"):
            cand = None
            if tail == "Timer" and len(node.args) >= 2:
                cand = node.args[1]
            for kw in node.keywords:
                if kw.arg in ("target", "function"):
                    cand = kw.value
            if cand is not None:
                m, d = self._target_of(cand)
                if m or d:
                    self.thread_targets.append(
                        ThreadTarget("timer" if tail == "Timer" else "thread",
                                     m, d, node.lineno))
        elif isinstance(node.func, ast.Attribute) and node.func.attr == "submit" \
                and node.args:
            m, d = self._target_of(node.args[0])
            if m or d:
                self.thread_targets.append(ThreadTarget("submit", m, d, node.lineno))
        elif tail == "register_message_receive_handler" and len(node.args) >= 2:
            m, d = self._target_of(node.args[1])
            if m or d:
                self.thread_targets.append(ThreadTarget("handler", m, d, node.lineno))
        elif tail == "add_comm_event_sink" and node.args:
            m, d = self._target_of(node.args[0])
            if m or d:
                self.thread_targets.append(ThreadTarget("sink", m, d, node.lineno))


def scan_function(fn, self_locks: dict[str, str], mod_locks: dict[str, str],
                  relpath: str, cls_name: Optional[str]) -> FunctionScan:
    scan = FunctionScan(self_locks, mod_locks, relpath, cls_name)
    for stmt in fn.body:
        scan.visit(stmt)
    return scan
