"""Shared module walker + rule runner behind ``fedml-tpu lint``.

Every ``.py`` file under the target package parses ONCE into a
:class:`ModuleInfo` (AST + source + suppression map); each rule then visits
the shared trees.  Rules are two-phase: :meth:`Rule.check_module` per module,
then :meth:`Rule.finalize` with the full module list for cross-module
invariants (GL001's dead-declaration check needs every read site in the
package before it can call a declaration dead).

Suppression scoping happens here, not in the rules: a
``# graftlint: disable=GLxxx`` on a ``def``/``class`` line covers the whole
body, so "caller holds the lock" methods carry ONE annotated suppression
instead of one per line.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field as dc_field
from pathlib import Path
from typing import Iterable, Optional, Sequence

from .findings import Finding, load_baseline, parse_suppressions


class ModuleInfo:
    """One parsed module: path, AST, source, and the expanded suppression map."""

    def __init__(self, relpath: str, source: str, tree: Optional[ast.Module] = None):
        self.relpath = relpath  # posix, relative to the linted package root
        self.source = source
        self.tree = tree if tree is not None else ast.parse(source, filename=relpath)
        # line -> rule ids silenced there; def/class-line directives expand
        # to the node's whole span so one annotation covers a method
        self._suppressions = parse_suppressions(source)
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                ids = self._suppressions.get(node.lineno)
                if ids:
                    for line in range(node.lineno, (node.end_lineno or node.lineno) + 1):
                        self._suppressions.setdefault(line, set()).update(ids)

    def is_suppressed(self, rule_id: str, line: int) -> bool:
        return rule_id in self._suppressions.get(line, ())


class Rule:
    """Base rule plugin: an id, a one-line title, and the two visit hooks."""

    id: str = "GL000"
    title: str = ""

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        return ()

    def finalize(self, modules: Sequence[ModuleInfo]) -> Iterable[Finding]:
        return ()


@dataclass
class LintResult:
    findings: list[Finding]                      # active (not suppressed/baselined)
    suppressed: list[Finding] = dc_field(default_factory=list)
    baselined: list[Finding] = dc_field(default_factory=list)
    errors: list[str] = dc_field(default_factory=list)  # unparseable files

    @property
    def ok(self) -> bool:
        return not self.findings and not self.errors

    def counts_by_rule(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def render(self) -> str:
        lines = [f.render() for f in self.findings]
        lines += [f"lint: failed to parse {e}" for e in self.errors]
        status = "clean" if self.ok else f"{len(self.findings)} finding(s)"
        tail = (f"lint: {status}"
                f" ({len(self.suppressed)} suppressed, {len(self.baselined)} baselined)")
        return "\n".join(lines + [tail])


def default_rules() -> list[Rule]:
    from .rules import ALL_RULES

    return [cls() for cls in ALL_RULES]


def iter_modules(root: str | Path) -> tuple[list[ModuleInfo], list[str]]:
    """Parse every ``*.py`` under ``root`` (or the single file ``root``).
    Returns (modules, unparseable-file descriptions)."""
    rootp = Path(root)
    paths = [rootp] if rootp.is_file() else sorted(rootp.rglob("*.py"))
    modules, errors = [], []
    for p in paths:
        if "__pycache__" in p.parts:
            continue
        rel = p.name if rootp.is_file() else p.relative_to(rootp).as_posix()
        try:
            modules.append(ModuleInfo(rel, p.read_text()))
        except (SyntaxError, UnicodeDecodeError) as e:
            errors.append(f"{rel}: {e}")
    return modules, errors


def run_lint(root: str | Path, rules: Optional[Sequence[Rule]] = None,
             baseline: Optional[str | Path] = None) -> LintResult:
    """The full pass: parse package, run every rule, split findings into
    active / inline-suppressed / baselined."""
    modules, errors = iter_modules(root)
    by_rel = {m.relpath: m for m in modules}
    rules = list(rules) if rules is not None else default_rules()
    raw: list[Finding] = []
    for rule in rules:
        for mod in modules:
            raw.extend(rule.check_module(mod))
        raw.extend(rule.finalize(modules))
    baseline_keys = load_baseline(baseline) if baseline else set()
    result = LintResult(findings=[], errors=errors)
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule, f.symbol)):
        mod = by_rel.get(f.path)
        if mod is not None and mod.is_suppressed(f.rule, f.line):
            result.suppressed.append(f)
        elif f.key in baseline_keys:
            result.baselined.append(f)
        else:
            result.findings.append(f)
    return result


# -- tiny shared AST helpers used by several rules ---------------------------

def dotted_name(node: ast.AST) -> str:
    """'jax.lax.scan' for Attribute/Name chains; '' when not a plain chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
