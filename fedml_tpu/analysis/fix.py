"""``fedml-tpu lint --fix`` — mechanical migration of legacy ``extra.get``
idioms to ``cfg_extra(cfg, name, default)``.

GL001 flags three legacy read idioms; this module REWRITES the one that has a
semantics-preserving mechanical form — the ``.get`` call::

    cfg.extra.get("fused_blocks")                     -> cfg_extra(cfg, 'fused_blocks', None)
    (getattr(cfg, "extra", {}) or {}).get("k", 3)     -> cfg_extra(cfg, 'k', 3)
    extra = cfg.extra; ... extra.get("silo_dp", True) -> cfg_extra(cfg, 'silo_dp', True)
    x = extra.setdefault("k", 3)                      -> x = cfg_extra(cfg, 'k', 3)
    x = cfg.extra["k"]                                -> x = cfg_extra(cfg, 'k', None)
    if "k" in cfg.extra: ...                          -> if cfg_extra_present(cfg, 'k'): ...
    if "k" not in extra: ...                          -> if (not cfg_extra_present(cfg, 'k')): ...
    cfg.extra["k"] = v                                -> set_cfg_extra(cfg, 'k', v)

The original default expression is carried verbatim (``.get`` with no default
becomes an explicit ``None``), so the rewrite never swaps in the registry
default where the old code returned ``None`` — behavior is identical, the
read just becomes registry-checked.

``setdefault`` in VALUE position is rewritten too (the ROADMAP carried
item): the read half is exactly ``cfg_extra`` with the same default, and
the dict-seeding side effect is what the registry replaces — every other
registry-backed read supplies its own declared default, so the seed is
dead weight.  A *statement*-position ``extra.setdefault(k, v)`` exists ONLY
for that side effect (someone downstream reads the dict raw); it is
rewritten to an EXPLICIT seed through the registry-checked write::

    cfg.extra.setdefault("k", 3)   ->   set_cfg_extra(cfg, 'k', cfg_extra(cfg, 'k', 3))

which preserves the seeded dict for every raw downstream reader (present
key keeps its value via the ``cfg_extra`` resolution order, missing key
lands the same default) while the flag name becomes declared and
GL001-checked on BOTH halves.

Value-position ``extra["k"]`` subscript READS are rewritten to
``cfg_extra(cfg, 'k', None)`` (ISSUE 12 satellite).  This is the one rewrite
that intentionally changes missing-key behavior: the subscript raised
``KeyError`` where ``cfg_extra`` returns ``None`` — but a flag read that
crashes on an unset flag is exactly the misconfiguration failure mode the
registry exists to kill, and every rewritten name becomes a declared,
GL001-checked read.  Set keys behave identically (proven by test).

``"k" in extra`` / ``"k" not in extra`` membership tests are rewritten to
``cfg_extra_present(cfg, 'k')`` (ISSUE 20 satellite) — the dedicated
membership probe keeps present-but-``None`` distinct from absent, so the
rewrite is semantics-preserving wherever the attribute-vs-dict resolution
order agrees (the same alignment every other rewrite already accepts).
The ``not in`` form is paren-wrapped so operator precedence survives any
surrounding expression.  Single-target ``extra["k"] = value`` STORES
become ``set_cfg_extra(cfg, 'k', value)`` — the one blessed write idiom,
registry-checked like the reads.

Sites the fixer cannot prove out — statement-position subscript reads,
Del/augmented targets, non-literal flag names, and receivers whose owning
config expression cannot be recovered — are reported for manual
migration, never guessed at.

``fix_source`` loops to a fixpoint (a ``.get`` nested inside another's
default argument is rewritten on the next pass), which is also what makes
``--fix`` idempotent: a second run over fixed sources reports zero rewrites.
The inserted import is the absolute ``from fedml_tpu.core.flags import
<helpers actually used>`` — the package itself migrated in PR 5, so the
fixer's targets are out-of-tree recipes/plugins where a relative import
would not resolve.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field as dc_field
from pathlib import Path
from typing import Callable, Optional

from .engine import ModuleInfo, dotted_name, str_const
from .rules.gl001_flags import _is_extra_expr

__all__ = ["fix_source", "fix_file", "fix_tree", "FixResult"]

IMPORT_MODULE = "fedml_tpu.core.flags"
#: canonical order for the inserted import's name list (and the detection
#: of what an existing import already provides)
HELPER_NAMES = ("cfg_extra", "cfg_extra_present", "set_cfg_extra")
IMPORT_LINE = f"from {IMPORT_MODULE} import cfg_extra"  # the common single-helper form


@dataclass
class FixResult:
    files_changed: list[str] = dc_field(default_factory=list)
    rewrites: int = 0
    skipped: list[str] = dc_field(default_factory=list)  # manual-migration notes

    def render(self) -> str:
        lines = [f"fixed {self.rewrites} legacy extra read(s) in "
                 f"{len(self.files_changed)} file(s)"]
        lines += [f"  rewrote: {p}" for p in self.files_changed]
        lines += [f"  manual:  {s}" for s in self.skipped]
        return "\n".join(lines)


def _cfg_expr_of(node: ast.AST, assigned: dict[str, Optional[str]]) -> Optional[str]:
    """Recover the source of the config object that owns this extra-like
    expression (``cfg.extra`` -> ``cfg``); None when it cannot be proven."""
    if isinstance(node, ast.Attribute) and node.attr == "extra":
        try:
            return ast.unparse(node.value)
        except Exception:
            return None
    if isinstance(node, ast.Call):
        fn = dotted_name(node.func)
        if fn == "getattr" and len(node.args) >= 2 and str_const(node.args[1]) == "extra":
            try:
                return ast.unparse(node.args[0])
            except Exception:
                return None
        if fn == "dict" and node.args:
            return _cfg_expr_of(node.args[0], assigned)
        return None
    if isinstance(node, ast.BoolOp):
        for v in node.values:
            out = _cfg_expr_of(v, assigned)
            if out is not None:
                return out
        return None
    if isinstance(node, ast.Name):
        return assigned.get(node.id)
    return None


def _line_offsets(source: str) -> list[int]:
    offsets, total = [0], 0
    for line in source.splitlines(keepends=True):
        total += len(line)
        offsets.append(total)
    return offsets


def _span(node: ast.AST, offsets: list[int]) -> tuple[int, int]:
    return (offsets[node.lineno - 1] + node.col_offset,
            offsets[node.end_lineno - 1] + node.end_col_offset)


def _one_pass(source: str, relpath: str,
              suppressed: Callable[[int], bool]) -> tuple[str, int, list[str]]:
    """One rewrite sweep: outermost ``.get`` candidates only (nested ones are
    caught by the fixpoint loop in :func:`fix_source`)."""
    tree = ast.parse(source)
    offsets = _line_offsets(source)
    # expressions whose value is discarded (bare expression statements): a
    # setdefault here exists only for its dict-seeding side effect, and a
    # bare subscript read has no value consumer to migrate
    stmt_position = {
        id(stmt.value) for stmt in ast.walk(tree)
        if isinstance(stmt, ast.Expr)
        and isinstance(stmt.value, (ast.Call, ast.Subscript))
    }
    extra_vars: set[str] = set()
    assigned: dict[str, Optional[str]] = {}
    # (span, replacement, helpers the replacement calls)
    candidates: list[tuple[tuple[int, int], str, tuple[str, ...]]] = []
    skipped: list[str] = []
    imported = {
        a.name
        for n in ast.walk(tree) if isinstance(n, ast.ImportFrom)
        for a in n.names if a.name in HELPER_NAMES
    }

    def skip(node: ast.AST, why: str) -> None:
        if not suppressed(node.lineno):
            skipped.append(f"{relpath}:{node.lineno}: {why}")

    for node in ast.walk(tree):
        if getattr(node, "lineno", None) is not None and suppressed(node.lineno):
            # an annotated `# graftlint: disable=GL001(...)` site is a
            # deliberate exception — neither rewritten nor nagged about
            continue
        # mirror GL001's tracking of `extra = <extra-like>` locals, keeping
        # the recovered cfg expression alongside
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and _is_extra_expr(node.value, extra_vars):
            extra_vars.add(node.targets[0].id)
            assigned[node.targets[0].id] = _cfg_expr_of(node.value, assigned)
            continue
        # single-target subscript STORE on an extra-like receiver: the whole
        # statement becomes the registry-checked write (ISSUE 20 satellite)
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Subscript) \
                and _is_extra_expr(node.targets[0].value, extra_vars):
            sub = node.targets[0]
            name = str_const(sub.slice)
            if name is None:
                skip(node, "extra[<non-literal name>] = ... store — GL001 needs "
                           "a literal flag name; migrate by hand")
                continue
            cfg_src = _cfg_expr_of(sub.value, assigned)
            if cfg_src is None:
                skip(node, f"extra[{name!r}] = ... store: owning config object "
                           "not recoverable — migrate by hand")
                continue
            value_src = ast.unparse(node.value)
            candidates.append((_span(node, offsets),
                               f"set_cfg_extra({cfg_src}, {name!r}, {value_src})",
                               ("set_cfg_extra",)))
            continue
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.args and _is_extra_expr(node.func.value, extra_vars):
            if node.func.attr == "setdefault" and id(node) in stmt_position:
                # statement-position seed: rewrite to an explicit seed through
                # the registry-checked write — the seeded dict stays seeded
                # for raw downstream readers, the name becomes a declared
                # GL001-checked flag on both the read and write halves
                name = str_const(node.args[0])
                cfg_src = _cfg_expr_of(node.func.value, assigned)
                if (name is None or cfg_src is None
                        or len(node.args) > 2 or node.keywords):
                    skip(node, "statement-position extra.setdefault(...) with a "
                               "non-literal name / unrecoverable config / odd "
                               "call shape — migrate by hand")
                    continue
                default_src = (ast.unparse(node.args[1])
                               if len(node.args) == 2 else "None")
                candidates.append((_span(node, offsets),
                                   f"set_cfg_extra({cfg_src}, {name!r}, "
                                   f"cfg_extra({cfg_src}, {name!r}, {default_src}))",
                                   ("cfg_extra", "set_cfg_extra")))
                continue
            if node.func.attr not in ("get", "setdefault"):
                continue
            verb = node.func.attr
            name = str_const(node.args[0])
            if name is None:
                skip(node, f"extra.{verb}(<non-literal name>) — GL001 needs a "
                           "literal flag name; migrate by hand")
                continue
            cfg_src = _cfg_expr_of(node.func.value, assigned)
            if cfg_src is None:
                skip(node, f"extra.{verb}({name!r}): owning config object not "
                           "recoverable — migrate by hand")
                continue
            if len(node.args) > 2 or node.keywords:
                skip(node, f"extra.{verb}({name!r}, ...): unexpected call shape — "
                           "migrate by hand")
                continue
            default_src = ast.unparse(node.args[1]) if len(node.args) == 2 else "None"
            replacement = f"cfg_extra({cfg_src}, {name!r}, {default_src})"
            candidates.append((_span(node, offsets), replacement, ("cfg_extra",)))
        elif isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load) \
                and _is_extra_expr(node.value, extra_vars):
            if id(node) in stmt_position:
                skip(node, "statement-position extra[...] has no value use — "
                           "migrate (or delete) the site by hand")
                continue
            name = str_const(node.slice)
            if name is None:
                skip(node, f"extra[{ast.unparse(node.slice)}] — GL001 needs a "
                           "literal flag name; migrate by hand")
                continue
            cfg_src = _cfg_expr_of(node.value, assigned)
            if cfg_src is None:
                skip(node, f"extra[{name!r}]: owning config object not "
                           "recoverable — migrate by hand")
                continue
            # value-position subscript read: becomes the registry-checked
            # read with default None (missing key: KeyError -> None — the
            # deliberate semantics change documented in the module docstring)
            candidates.append(
                (_span(node, offsets), f"cfg_extra({cfg_src}, {name!r}, None)",
                 ("cfg_extra",)))
        elif isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                and _is_extra_expr(node.comparators[0], extra_vars):
            # membership test: becomes the dedicated registry-checked probe
            # (cfg_extra_present keeps present-but-None distinct from absent,
            # so the rewrite preserves the dict-membership semantics)
            name = str_const(node.left)
            if name is None:
                skip(node, "membership test with a non-literal name — "
                           "migrate by hand")
                continue
            cfg_src = _cfg_expr_of(node.comparators[0], assigned)
            if cfg_src is None:
                skip(node, f"{name!r} in extra: owning config object not "
                           "recoverable — migrate by hand")
                continue
            repl = f"cfg_extra_present({cfg_src}, {name!r})"
            if isinstance(node.ops[0], ast.NotIn):
                # paren-wrapped so precedence survives any surrounding context
                repl = f"(not {repl})"
            candidates.append((_span(node, offsets), repl, ("cfg_extra_present",)))

    # outermost candidates only: an inner .get inside another's default arg
    # is regenerated by the outer rewrite and picked up on the next pass
    candidates.sort(key=lambda c: c[0][0])
    chosen: list[tuple[tuple[int, int], str, tuple[str, ...]]] = []
    last_end = -1
    for (start, end), repl, helpers in candidates:
        if start < last_end:
            continue
        chosen.append(((start, end), repl, helpers))
        last_end = end

    if not chosen:
        return source, 0, skipped
    out = source
    for (start, end), repl, _helpers in sorted(
            chosen, key=lambda c: c[0][0], reverse=True):
        out = out[:start] + repl + out[end:]
    used = {h for _, _, helpers in chosen for h in helpers}
    missing = [h for h in HELPER_NAMES if h in used and h not in imported]
    if missing:
        out = _insert_import(out, missing)
    return out, len(chosen), skipped


def _insert_import(source: str, names: "list[str] | None" = None) -> str:
    """Insert the flags-helper import (only the names actually needed) after
    the leading docstring/import block."""
    tree = ast.parse(source)
    insert_after = 0
    for i, stmt in enumerate(tree.body):
        if i == 0 and isinstance(stmt, ast.Expr) \
                and isinstance(stmt.value, ast.Constant) \
                and isinstance(stmt.value.value, str):
            insert_after = stmt.end_lineno or stmt.lineno
            continue
        if isinstance(stmt, (ast.Import, ast.ImportFrom)):
            insert_after = stmt.end_lineno or stmt.lineno
            continue
        break
    line = (IMPORT_LINE if not names
            else f"from {IMPORT_MODULE} import {', '.join(names)}")
    lines = source.splitlines(keepends=True)
    pos = sum(len(l) for l in lines[:insert_after])
    sep = "\n" if insert_after else ""
    return source[:pos] + sep + line + "\n" + source[pos:]


def fix_source(source: str, relpath: str = "<string>",
               max_passes: int = 10) -> tuple[str, int, list[str]]:
    """Rewrite to a fixpoint.  Returns (new_source, total_rewrites, skipped);
    re-running on the output always yields zero rewrites (idempotence).
    Lines under a ``# graftlint: disable=GL001`` suppression are left alone."""
    total, skipped = 0, []
    for _ in range(max_passes):
        mod = ModuleInfo(relpath, source)  # suppression map tracks each pass
        source, n, skipped = _one_pass(
            source, relpath, lambda line: mod.is_suppressed("GL001", line))
        total += n
        if n == 0:
            break
    return source, total, skipped


def fix_file(path: Path, result: FixResult, root: Optional[Path] = None) -> None:
    rel = path.relative_to(root).as_posix() if root else path.name
    try:
        src = path.read_text()
        new, n, skipped = fix_source(src, rel)
    except (SyntaxError, UnicodeDecodeError, OSError) as e:
        result.skipped.append(f"{rel}: unfixable ({type(e).__name__}: {e})")
        return
    result.skipped.extend(skipped)
    if n:
        path.write_text(new)
        result.files_changed.append(rel)
        result.rewrites += n


def fix_tree(root: str | Path) -> FixResult:
    """Fix every ``*.py`` under ``root`` (or the single file) in place.  The
    registry module itself is exempt — its one ``extra.get`` IS the accessor."""
    rootp = Path(root)
    result = FixResult()
    paths = [rootp] if rootp.is_file() else sorted(rootp.rglob("*.py"))
    for p in paths:
        if "__pycache__" in p.parts:
            continue
        if p.as_posix().endswith("core/flags.py"):
            continue
        fix_file(p, result, root=None if rootp.is_file() else rootp)
    return result
