"""Per-kernel wall-time observation for Pallas entry points.

Every hand-written kernel records its eager invocations into the
process-global ``fedml_pallas_kernel_seconds`` histogram (ROADMAP
"Pallas-level timing hooks"), labeled by kernel name — scrapable via
``/metrics`` and summarized by ``fedml-tpu obs report`` / ``bench.py``.
(The record name shipped over the obs trail stays ``pallas_kernel_seconds``
— a wire/trail format; the registry family carries the ``fedml_`` namespace
the metric-name lint enforces.)

Only *eager* calls are observed: inside ``jit``/``vmap``/``scan`` the
arguments are tracers and host wall-clock around the call would measure
tracing, not execution (per-invocation device time for traced kernels comes
from ``scripts/profile_trace.py`` on the chip).  Eager observation blocks on
the kernel's outputs — the callers that hit this path (compression round
trips, bench microbenches) consume the result immediately anyway.
"""

from __future__ import annotations

import time

import jax

from ...obs import registry as obsreg

PALLAS_KERNEL_TIME = obsreg.REGISTRY.histogram(
    "fedml_pallas_kernel_seconds",
    "Wall time of eagerly-invoked Pallas kernels (dispatch to ready), "
    "labeled by kernel.",
    labels=("kernel",),
)


#: extra per-observation sinks ``fn(kernel_name, seconds)`` — e.g. the
#: cross-silo client forwards observations over the FL transport so they land
#: in the server's collector trail (and thus in ``fedml-tpu obs report``)
_sinks: list = []


def add_sink(fn):
    _sinks.append(fn)
    return fn


def remove_sink(fn) -> None:
    try:
        _sinks.remove(fn)
    except ValueError:
        pass


def observe_eager(name: str, fn, *args):
    """Run ``fn(*args)``; when the call is eager (no tracers among the
    argument leaves), time it to completion and record under ``name``."""
    if any(isinstance(l, jax.core.Tracer) for l in jax.tree_util.tree_leaves(args)):
        return fn(*args)
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    PALLAS_KERNEL_TIME.observe(dt, kernel=name)
    for sink in list(_sinks):
        try:
            sink(name, dt)
        except Exception:
            pass  # telemetry must never take down the kernel path
    return out


def kernel_time_summary() -> dict:
    """{kernel: {count, total_s, mean_s}} from the process-global histogram —
    the JSON-friendly view ``bench.py`` attaches to its results."""
    out = {}
    with PALLAS_KERNEL_TIME._lock:
        children = {k: dict(v) for k, v in PALLAS_KERNEL_TIME._children.items()}
    for key, child in sorted(children.items()):
        n = int(child["count"])
        total = float(child["sum"])
        out[key[0]] = {
            "count": n,
            "total_s": round(total, 6),
            "mean_s": round(total / n, 6) if n else 0.0,
        }
    return out
