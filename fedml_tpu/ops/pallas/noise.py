"""Pallas TPU kernel: fused DP noise application for secure-aggregation
finalize.

The streaming SecAgg path (ISSUE 15) adds central-DP noise EXACTLY ONCE, at
finalize, to the unmasked aggregate — never per client, never per fold.  The
fused kernel keeps each block VMEM-resident through the scale-and-add
(one HBM read of the aggregate + one of the noise, one write), instead of
XLA materializing the scaled-noise intermediate.

Same discipline as ``quantize.py``: the normal noise is an EXPLICIT input
generated with the caller's jax PRNG key — the kernel stays deterministic
given its inputs, bitwise reproducible across interpret (CPU CI) and
compiled (TPU) modes, and testable against the pure-jnp reference below.
(TPU Pallas does have an in-kernel PRNG — ``pltpu.prng_random_bits`` — but
an in-kernel stream cannot be replayed by the interpret-mode oracle, and DP
accounting wants the noise draw auditable from the round key.)
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .timing import observe_eager

_SUB, _LANE = 8, 128  # f32 min tile
_BLOCK = _SUB * _LANE


def _noise_kernel(x_ref, noise_ref, sigma_ref, out_ref):
    # sigma rides SMEM as a (1, 1) scalar; mul-then-add mirrors the
    # reference op-for-op so interpret mode is bitwise the jnp oracle
    out_ref[:] = x_ref[:] + noise_ref[:] * sigma_ref[0, 0]


def _pad_blocks(vec: jax.Array):
    n = vec.shape[0]
    pad = (-n) % _BLOCK
    x = jnp.pad(vec, (0, pad)).reshape(-1, _SUB, _LANE)
    return x, n


def apply_gaussian_noise(vec: jax.Array, key: jax.Array, sigma: float,
                         interpret: bool = False) -> jax.Array:
    """flat f32 vector + N(0, sigma^2) noise in one fused VMEM pass.
    ``interpret=True`` runs the same kernel through the pallas interpreter
    (CPU CI)."""
    return observe_eager(
        "apply_gaussian_noise", partial(_noise_impl, interpret=interpret),
        vec, key, jnp.float32(sigma),
    )


def _noise_impl(vec: jax.Array, key: jax.Array, sigma: jax.Array, *,
                interpret: bool) -> jax.Array:
    from jax.experimental.pallas import tpu as pltpu

    x, n = _pad_blocks(vec.astype(jnp.float32))
    noise = jax.random.normal(key, x.shape, jnp.float32)
    blocks = x.shape[0]
    out = pl.pallas_call(
        _noise_kernel,
        grid=(blocks,),
        in_specs=[
            pl.BlockSpec((1, _SUB, _LANE), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, _SUB, _LANE), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, _SUB, _LANE), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((blocks, _SUB, _LANE), jnp.float32),
        interpret=interpret,
    )(x, noise, sigma.reshape(1, 1))
    return out.reshape(-1)[:n]


# -- pure-jnp reference (the conformance oracle for the kernel) --------------

def apply_gaussian_noise_reference(vec: jax.Array, key: jax.Array,
                                   sigma: float) -> jax.Array:
    x, n = _pad_blocks(vec.astype(jnp.float32))
    noise = jax.random.normal(key, x.shape, jnp.float32)
    out = x + noise * jnp.float32(sigma)
    return out.reshape(-1)[:n]
