"""Pallas TPU kernels: fused stochastic int8 quantization for gradient
compression.

The FedSGD compression path (``ops/compression.py``, reference
``ml/utils/compression.py:175-260``) quantizes flat update vectors every
round; at cross-silo scale that is the bandwidth-critical op.  The fused
kernel keeps each block in VMEM through scale -> stochastic round -> int8
cast (one HBM read + one ~4x-smaller write), instead of XLA materializing
the f32 intermediates between ops.

Layout: the flat vector is reshaped to (blocks, 8, 128) — the f32 min tile —
with one grid step per block and a per-block scale (block-wise scaling is
also statistically tighter than one global scale).  The uniform noise for
stochastic rounding is an explicit input (generated with the caller's jax
PRNG key): this keeps the kernel deterministic given its inputs, bitwise
reproducible across interpret (CPU CI) and compiled (TPU) modes, and
testable against the pure-jnp reference below.

E[dequantize(quantize(x))] = x  (floor(x/s + u) with u ~ U[0,1) is unbiased).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .timing import observe_eager

_SUB, _LANE = 8, 128  # f32 min tile
_BLOCK = _SUB * _LANE


def _quantize_kernel(x_ref, noise_ref, values_ref, scale_ref):
    # scale_ref sees the WHOLE (blocks, 1) scale array in SMEM (per-block
    # (1,1) tiles violate the TPU (8,128) tiling constraint); each grid step
    # writes only its own element
    x = x_ref[:]
    amax = jnp.max(jnp.abs(x))
    scale = amax / 127.0 + 1e-12
    scale_ref[pl.program_id(0), 0] = scale
    scaled = x / scale                      # in [-127, 127]
    q = jnp.floor(scaled + noise_ref[:])    # stochastic round (unbiased)
    values_ref[:] = jnp.clip(q, -127.0, 127.0).astype(jnp.int8)


def _dequantize_kernel(values_ref, scale_ref, out_ref):
    out_ref[:] = values_ref[:].astype(jnp.float32) * scale_ref[pl.program_id(0), 0]


def _pad_blocks(vec: jax.Array):
    n = vec.shape[0]
    pad = (-n) % _BLOCK
    x = jnp.pad(vec, (0, pad)).reshape(-1, _SUB, _LANE)
    return x, n


def quantize_int8_stochastic(vec: jax.Array, key: jax.Array, interpret: bool = False):
    """flat f32 vector -> (int8 values (blocks, 8, 128), f32 scales (blocks,),
    original length).  ``interpret=True`` runs the same kernel through the
    pallas interpreter (CPU CI)."""
    return observe_eager(
        "quantize_int8_stochastic", partial(_quantize_impl, interpret=interpret),
        vec, key,
    )


def _quantize_impl(vec: jax.Array, key: jax.Array, *, interpret: bool):
    x, n = _pad_blocks(vec.astype(jnp.float32))
    noise = jax.random.uniform(key, x.shape, jnp.float32)
    blocks = x.shape[0]
    values, scales = pl.pallas_call(
        _quantize_kernel,
        grid=(blocks,),
        in_specs=[
            pl.BlockSpec((1, _SUB, _LANE), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, _SUB, _LANE), lambda i: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, _SUB, _LANE), lambda i: (i, 0, 0)),
            pl.BlockSpec((blocks, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((blocks, _SUB, _LANE), jnp.int8),
            jax.ShapeDtypeStruct((blocks, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, noise)
    return values, scales[:, 0], n


def dequantize_int8(values: jax.Array, scales: jax.Array, length: int,
                    interpret: bool = False) -> jax.Array:
    return observe_eager(
        "dequantize_int8",
        partial(_dequantize_impl, length=length, interpret=interpret),
        values, scales,
    )


def _dequantize_impl(values: jax.Array, scales: jax.Array, *, length: int,
                     interpret: bool) -> jax.Array:
    blocks = values.shape[0]
    out = pl.pallas_call(
        _dequantize_kernel,
        grid=(blocks,),
        in_specs=[
            pl.BlockSpec((1, _SUB, _LANE), lambda i: (i, 0, 0)),
            pl.BlockSpec((blocks, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, _SUB, _LANE), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((blocks, _SUB, _LANE), jnp.float32),
        interpret=interpret,
    )(values, scales[:, None])
    return out.reshape(-1)[:length]


# -- pure-jnp reference (the conformance oracle for the kernel) --------------

def quantize_int8_reference(vec: jax.Array, key: jax.Array):
    x, n = _pad_blocks(vec.astype(jnp.float32))
    noise = jax.random.uniform(key, x.shape, jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=(1, 2), keepdims=True)
    scale = amax / 127.0 + 1e-12
    q = jnp.clip(jnp.floor(x / scale + noise), -127.0, 127.0).astype(jnp.int8)
    return q, scale[:, 0, 0], n


def qsgd_int8(vec: jax.Array, key: jax.Array, interpret: bool = False) -> jax.Array:
    """Quantize + dequantize round trip — the simulation-path compressor
    (dense-in/dense-out like ops/compression.qsgd, but int8 block-scaled and
    kernel-fused)."""
    values, scales, n = quantize_int8_stochastic(vec, key, interpret=interpret)
    return dequantize_int8(values, scales, n, interpret=interpret)
