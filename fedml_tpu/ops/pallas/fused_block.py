"""Pallas TPU kernel: fused BasicBlock epilogue (BN apply + residual + ReLU).

The round-4 trace (PERF.md "Per-op attribution") pins 33 ms of the 284 ms
flagship FedAvg round on *second-pass loop fusions*: after each conv, XLA
materializes the BN scale/shift application, the residual/downsample add and
the ReLU as separate HBM traversals of the full activation tensor.  This
kernel fuses that epilogue into ONE pass that keeps the block's activations
in VMEM — one HBM read of the conv output (+ residual), one write of the
activated result — the same "intermediates stay on-chip" discipline as
``quantize.py`` and the FlashAttention lineage (PAPERS.md).

Scope note: the batch mean/var *statistics* are NOT recomputed here — the
trace shows XLA already fuses those reductions into the producing conv
(``convert_reduce`` inside the conv fusions).  The caller folds
(gamma, beta, mean, var) into a per-channel affine ``scale``/``shift``
(``models/resnet.FusedBasicBlock``) and this kernel applies it.  Gradients
w.r.t. ``scale``/``shift`` chain back through mean/var into the conv output
via ordinary autodiff outside the kernel, so train-mode BN semantics are
exact.

Layout: activations are flattened and reshaped to ``(blocks, 16, 128)``
(16 sublanes covers the bf16 min tile; f32's 8 divides it).  Because every
CIFAR-ResNet channel count C ∈ {16, 32, 64} divides the 128-lane vector
width, a flat element's channel is ``lane % C`` — so the per-channel affine
rides a single (1, 1, 128) lane vector (``scale`` tiled 128/C times) and the
backward pass accumulates d(scale)/d(shift) into one (1, 16, 128) VMEM tile
across grid steps, folded to (C,) outside the kernel.  Channels that do not
divide 128 fall back to the pure-jnp reference (same math, XLA-fused).

The backward pass is also a single fused traversal.  The ReLU mask is not
stored separately: the forward *output* is saved (XLA aliases it — it is the
layer's activation and already lives in HBM for the bwd convs) and the mask
is recovered as ``out > 0``, which is exactly ``jax.nn.relu``'s subgradient
convention (zero at the kink).

``interpret=True`` runs the identical kernels through the Pallas interpreter
for CPU CI; when ``interpret`` is not given, it is derived from the active
backend (compiled on TPU, interpreted elsewhere), matching
``ops/compression.qsgd_int8_fused``.  Parity oracle: ``fused_block_reference``
— jitted kernel vs jitted reference is f32-bitwise (the parity tests in
``tests/test_pallas.py`` assert it).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .timing import observe_eager

_SUB, _LANE = 16, 128  # sublane x lane block; 16 covers the bf16 min tile
_BLOCK = _SUB * _LANE


def _supported(channels: int) -> bool:
    return channels <= _LANE and _LANE % channels == 0


def _to_blocks(a: jax.Array):
    n = a.size
    pad = (-n) % _BLOCK
    return jnp.pad(a.reshape(-1), (0, pad)).reshape(-1, _SUB, _LANE), n


def _lane_vec(v: jax.Array) -> jax.Array:
    """(C,) per-channel vector -> (1, 1, 128) lane vector.  With C | 128 a
    flat NHWC element's channel is ``lane % C``, so tiling 128/C copies makes
    the lane vector line up with every (16, 128) block."""
    return jnp.tile(v.astype(jnp.float32), _LANE // v.shape[-1]).reshape(1, 1, _LANE)


def _block_spec(index_map):
    return pl.BlockSpec((1, _SUB, _LANE), index_map)


def _lane_spec():
    return pl.BlockSpec((1, 1, _LANE), lambda i: (0, 0, 0))


# -- forward kernels ---------------------------------------------------------

def _fwd_res_kernel(y_ref, s_ref, b_ref, r_ref, out_ref):
    y = y_ref[...].astype(jnp.float32)
    z = y * s_ref[...] + b_ref[...] + r_ref[...].astype(jnp.float32)
    out_ref[...] = jnp.maximum(z, 0.0).astype(out_ref.dtype)


def _fwd_kernel(y_ref, s_ref, b_ref, out_ref):
    y = y_ref[...].astype(jnp.float32)
    z = y * s_ref[...] + b_ref[...]
    out_ref[...] = jnp.maximum(z, 0.0).astype(out_ref.dtype)


def _fwd_call(y, scale, shift, residual, interpret: bool):
    yb, n = _to_blocks(y)
    blocks = yb.shape[0]
    operands = [yb, _lane_vec(scale), _lane_vec(shift)]
    in_specs = [_block_spec(lambda i: (i, 0, 0)), _lane_spec(), _lane_spec()]
    kernel = _fwd_kernel
    if residual is not None:
        rb, _ = _to_blocks(residual)
        operands.append(rb)
        in_specs.append(_block_spec(lambda i: (i, 0, 0)))
        kernel = _fwd_res_kernel
    out = pl.pallas_call(
        kernel,
        grid=(blocks,),
        in_specs=in_specs,
        out_specs=_block_spec(lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(yb.shape, y.dtype),
        interpret=interpret,
    )(*operands)
    return out.reshape(-1)[:n].reshape(y.shape)


# -- backward kernels --------------------------------------------------------
#
# Accumulator outputs map every grid step onto the SAME (1, 16, 128) tile
# (TPU grids run sequentially; step 0 zero-initializes).  Padded tail
# elements contribute nothing: the cotangent g is zero-padded, so
# g * mask * (...) vanishes there.

def _bwd_res_kernel(g_ref, y_ref, s_ref, out_ref, dy_ref, dr_ref, ds_ref, db_ref):
    g = g_ref[...].astype(jnp.float32)
    mask = (out_ref[...] > 0).astype(jnp.float32)
    gm = g * mask
    dy_ref[...] = (gm * s_ref[...]).astype(dy_ref.dtype)
    dr_ref[...] = gm.astype(dr_ref.dtype)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        ds_ref[...] = jnp.zeros_like(ds_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    ds_ref[...] += gm * y_ref[...].astype(jnp.float32)
    db_ref[...] += gm


def _bwd_kernel(g_ref, y_ref, s_ref, out_ref, dy_ref, ds_ref, db_ref):
    g = g_ref[...].astype(jnp.float32)
    mask = (out_ref[...] > 0).astype(jnp.float32)
    gm = g * mask
    dy_ref[...] = (gm * s_ref[...]).astype(dy_ref.dtype)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        ds_ref[...] = jnp.zeros_like(ds_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    ds_ref[...] += gm * y_ref[...].astype(jnp.float32)
    db_ref[...] += gm


def _fold_lanes(acc: jax.Array, channels: int) -> jax.Array:
    """(1, 16, 128) f32 accumulator -> (C,): sum sublanes and the 128/C lane
    repeats (lane = k*C + c holds channel c)."""
    return acc.reshape(_SUB, _LANE // channels, channels).sum(axis=(0, 1))


def _bwd_call(g, y, scale, out, with_residual: bool, interpret: bool):
    channels = scale.shape[-1]
    gb, n = _to_blocks(g)
    yb, _ = _to_blocks(y)
    ob, _ = _to_blocks(out)
    blocks = gb.shape[0]
    elem = _block_spec(lambda i: (i, 0, 0))
    acc = _block_spec(lambda i: (0, 0, 0))
    acc_shape = jax.ShapeDtypeStruct((1, _SUB, _LANE), jnp.float32)
    if with_residual:
        dy, dr, ds, db = pl.pallas_call(
            _bwd_res_kernel,
            grid=(blocks,),
            in_specs=[elem, elem, _lane_spec(), elem],
            out_specs=[elem, elem, acc, acc],
            out_shape=[
                jax.ShapeDtypeStruct(gb.shape, y.dtype),
                jax.ShapeDtypeStruct(gb.shape, y.dtype),
                acc_shape,
                acc_shape,
            ],
            interpret=interpret,
        )(gb, yb, _lane_vec(scale), ob)
    else:
        dy, ds, db = pl.pallas_call(
            _bwd_kernel,
            grid=(blocks,),
            in_specs=[elem, elem, _lane_spec(), elem],
            out_specs=[elem, acc, acc],
            out_shape=[jax.ShapeDtypeStruct(gb.shape, y.dtype), acc_shape, acc_shape],
            interpret=interpret,
        )(gb, yb, _lane_vec(scale), ob)
        dr = None
    unblock = lambda a: a.reshape(-1)[:n].reshape(y.shape)
    dy = unblock(dy)
    dr = unblock(dr) if dr is not None else None
    return dy, _fold_lanes(ds, channels), _fold_lanes(db, channels), dr


# -- custom_vjp wiring -------------------------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(0,))
def _fused_res(interpret, y, scale, shift, residual):
    return _fwd_call(y, scale, shift, residual, interpret)


def _fused_res_fwd(interpret, y, scale, shift, residual):
    out = _fwd_call(y, scale, shift, residual, interpret)
    # residuals: the conv output y (needed for d scale), the folded scale and
    # the OUTPUT (whose sign is the relu mask) — all arrays XLA already
    # materializes, so nothing extra is written for the backward pass.  The
    # size-0 sentinels carry shift/residual dtypes (cotangent dtypes must
    # match primals exactly).
    return out, (y, scale, jnp.zeros((), shift.dtype), jnp.zeros((), residual.dtype), out)


def _fused_res_bwd(interpret, res, g):
    y, scale, shift0, r0, out = res
    dy, ds, db, dr = observe_eager(
        "fused_bn_residual_relu_bwd",
        partial(_bwd_call, with_residual=True, interpret=interpret),
        g, y, scale, out,
    )
    return dy, ds.astype(scale.dtype), db.astype(shift0.dtype), dr.astype(r0.dtype)


_fused_res.defvjp(_fused_res_fwd, _fused_res_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(0,))
def _fused(interpret, y, scale, shift):
    return _fwd_call(y, scale, shift, None, interpret)


def _fused_fwd(interpret, y, scale, shift):
    out = _fwd_call(y, scale, shift, None, interpret)
    return out, (y, scale, jnp.zeros((), shift.dtype), out)


def _fused_bwd(interpret, res, g):
    y, scale, shift0, out = res
    dy, ds, db, _ = observe_eager(
        "fused_bn_relu_bwd",
        partial(_bwd_call, with_residual=False, interpret=interpret),
        g, y, scale, out,
    )
    return dy, ds.astype(scale.dtype), db.astype(shift0.dtype)


_fused.defvjp(_fused_fwd, _fused_bwd)


# -- public API --------------------------------------------------------------

def _resolve_interpret(interpret) -> bool:
    if interpret is None:
        return jax.default_backend() != "tpu"
    return bool(interpret)


def fused_bn_relu(y: jax.Array, scale: jax.Array, shift: jax.Array,
                  *, interpret=None) -> jax.Array:
    """``relu(y * scale + shift)`` with per-channel (last-axis) affine, as one
    fused VMEM-resident pass; differentiable (fused backward)."""
    if not _supported(y.shape[-1]):
        return fused_block_reference(y, scale, shift)
    return observe_eager(
        "fused_bn_relu", partial(_fused, _resolve_interpret(interpret)),
        y, scale, shift,
    )


def fused_bn_residual_relu(y: jax.Array, scale: jax.Array, shift: jax.Array,
                           residual: jax.Array, *, interpret=None) -> jax.Array:
    """``relu(y * scale + shift + residual)`` — the full BasicBlock epilogue
    (BN apply, shortcut add, activation) as one fused pass; differentiable."""
    if not _supported(y.shape[-1]):
        return fused_block_reference(y, scale, shift, residual)
    return observe_eager(
        "fused_bn_residual_relu", partial(_fused_res, _resolve_interpret(interpret)),
        y, scale, shift, residual,
    )


# -- pure-jnp reference (the conformance oracle for the kernels) -------------

def fused_block_reference(y: jax.Array, scale: jax.Array, shift: jax.Array,
                          residual=None) -> jax.Array:
    z = y.astype(jnp.float32) * scale.astype(jnp.float32) + shift.astype(jnp.float32)
    if residual is not None:
        z = z + residual.astype(jnp.float32)
    return jnp.maximum(z, 0.0).astype(y.dtype)
