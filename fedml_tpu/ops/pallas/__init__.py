"""Pallas TPU kernels (hand-written hot ops the XLA autofuser can't shape).

Current kernels:
- ``quantize.quantize_int8_stochastic`` / ``dequantize_int8`` — fused
  block-scaled stochastic int8 gradient quantization for the FedSGD
  compression path.
"""

from .quantize import (
    dequantize_int8,
    qsgd_int8,
    quantize_int8_reference,
    quantize_int8_stochastic,
)

__all__ = [
    "dequantize_int8",
    "qsgd_int8",
    "quantize_int8_reference",
    "quantize_int8_stochastic",
]
