"""Pallas TPU kernels (hand-written hot ops the XLA autofuser can't shape).

Current kernels:
- ``quantize.quantize_int8_stochastic`` / ``dequantize_int8`` — fused
  block-scaled stochastic int8 gradient quantization for the FedSGD
  compression path.
- ``fused_block.fused_bn_relu`` / ``fused_bn_residual_relu`` — the fused
  BasicBlock epilogue (BN scale/shift apply + residual add + ReLU, with a
  fused custom-VJP backward) behind the ``fused_blocks`` recipe flag.

Every eager kernel invocation is recorded into the process-global
``pallas_kernel_seconds`` histogram (``timing.py``).
"""

from .fused_block import (
    fused_bn_relu,
    fused_bn_residual_relu,
    fused_block_reference,
)
from .quantize import (
    dequantize_int8,
    qsgd_int8,
    quantize_int8_reference,
    quantize_int8_stochastic,
)
from .timing import PALLAS_KERNEL_TIME, kernel_time_summary

__all__ = [
    "dequantize_int8",
    "fused_bn_relu",
    "fused_bn_residual_relu",
    "fused_block_reference",
    "kernel_time_summary",
    "PALLAS_KERNEL_TIME",
    "qsgd_int8",
    "quantize_int8_reference",
    "quantize_int8_stochastic",
]
