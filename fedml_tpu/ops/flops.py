"""Analytical FLOPs accounting + device peak lookup for MFU reporting.

MFU = (model FLOPs per second) / (chip peak FLOPs): the *nominal* FLOPs of the
training computation (fwd + bwd = 3x fwd for matmul-dominated nets), NOT the
executed FLOPs — rematerialization recompute does not count as useful work.
This is the PaLM-appendix convention the scaling literature uses (executed
FLOPs from XLA's cost model would over-credit remat recompute).

The reference has no MFU accounting anywhere (its perf story is wall-clock CI
budgets, SURVEY.md §6); BASELINE.md sets >=35% MFU as the target, so the
accounting itself is a new obligation of the TPU build.
"""

from __future__ import annotations

from typing import Optional

# bf16 peak FLOPs per chip by device_kind substring (first match wins).
# Sources: public TPU spec sheets (v4 275, v5e 197, v5p 459, v6e 918 TFLOPS).
_PEAK_TABLE = [
    ("v6 lite", 918e12),
    ("v6e", 918e12),
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
]


def device_peak_flops(device=None) -> Optional[float]:
    """Peak bf16 FLOPs/s of one chip, or None when unknown (e.g. CPU)."""
    import jax

    if device is None:
        device = jax.devices()[0]
    kind = (getattr(device, "device_kind", "") or "").lower()
    if getattr(device, "platform", "") not in ("tpu", "axon"):
        return None
    for needle, peak in _PEAK_TABLE:
        if needle in kind:
            return peak
    return None


def transformer_train_flops_per_token(
    n_params: int, n_embed_params: int, n_layers: int, d_model: int, seq_len: int
) -> float:
    """Nominal train FLOPs per token: 6*(matmul params) + attention term.

    ``n_embed_params`` (the gather-only embedding table) is excluded from the
    6N term; the lm_head projection participates in matmuls and stays in.
    The attention score/value matmuls add 12 * L * s * d (fwd 4*s*d per layer,
    x3 for fwd+bwd; counted un-halved since the dense kernel computes the full
    s^2 score matrix).
    """
    return 6.0 * (n_params - n_embed_params) + 12.0 * n_layers * seq_len * d_model


def resnet20_cifar_train_flops_per_sample() -> float:
    """ResNet-20 CIFAR-10 at 32x32: ~40.8M MACs fwd => 81.7 MFLOPs fwd,
    x3 for fwd+bwd.  (Conv MACs from the standard He et al. arch: 3 stages x
    3 blocks x 2 convs at 16/32/64 channels + stem + fc.)"""
    fwd = 81.7e6
    return 3.0 * fwd
