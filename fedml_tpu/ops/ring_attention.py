"""Ring attention — exact attention over sequence-sharded inputs.

Long-context is first-class here even though the reference has none
(SURVEY.md §5 "long-context: absent" — it only passes flash-attn flags to HF).
This is the blockwise-parallel / ring attention construction (Liu et al.,
"Ring Attention with Blockwise Transformers"): shard the sequence over a mesh
axis; K/V blocks rotate around the ring via ``jax.lax.ppermute`` while each
device keeps its Q block and maintains an online-softmax accumulator
(running max m, normalizer l, weighted sum o).  P steps of compute overlap
P-1 ICI hops; memory per device is O(seq/P), enabling sequences that never
fit one chip.

Causality is handled by global block offsets: a device skips (zero-masks)
K/V blocks strictly in its future.  The math is exact — identical (up to f32
reduction order) to full attention, verified in tests against the dense
reference implementation.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.mesh import SHARD_MAP_UNCHECKED, shard_map

NEG_INF = -1e30


def dense_attention(q, k, v, causal: bool = True, scale: Optional[float] = None):
    """Reference dense attention. q,k,v: (b, s, h, d) -> (b, s, h, d)."""
    b, s, h, d = q.shape
    scale = scale if scale is not None else d ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        mask = jnp.tril(jnp.ones((s, k.shape[1]), bool))
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32)).astype(q.dtype)


def _block_attn_accum(q, k, v, q_off, k_off, m, l, o, causal: bool, scale: float):
    """One blockwise online-softmax update.  q: (b, sq, h, d); k/v: (b, sk, h, d);
    m/l: (b, h, sq); o: (b, sq, h, d) f32 accumulators."""
    sq, sk = q.shape[1], k.shape[1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        q_pos = q_off + jnp.arange(sq)
        k_pos = k_off + jnp.arange(sk)
        mask = q_pos[:, None] >= k_pos[None, :]
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    m_new = jnp.maximum(m, logits.max(axis=-1))
    # rescale previous accumulators
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(logits - m_new[..., None])
    l_new = l * alpha + p.sum(axis=-1)
    o_new = o * jnp.transpose(alpha, (0, 2, 1))[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v.astype(jnp.float32)
    )
    return m_new, l_new, o_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
    dp_axis: Optional[str] = None,
    tp_axis: Optional[str] = None,
) -> jax.Array:
    """Exact attention with q/k/v sequence-sharded over ``mesh[axis]``.

    q, k, v: (batch, seq, heads, head_dim) GLOBAL shapes; the seq dim must be
    divisible by the axis size.  Returns the same global shape, seq-sharded.

    ``dp_axis``/``tp_axis``: optional batch / heads shardings so attention
    compute stays sharded on hybrid (data, model, seq) meshes instead of
    being all-gathered and replicated across those axes.
    """
    p_size = mesh.shape[axis]
    d = q.shape[-1]
    scale_ = scale if scale is not None else d ** -0.5
    if p_size == 1:
        return dense_attention(q, k, v, causal=causal, scale=scale_)

    def live(name, dim_size_index):
        if name is None or name not in mesh.shape or mesh.shape[name] <= 1:
            return None
        return name if q.shape[dim_size_index] % mesh.shape[name] == 0 else None

    dp = live(dp_axis, 0)
    tp = live(tp_axis, 2)
    spec = P(dp, axis, tp, None)

    def local_fn(q, k, v):
        # local shapes: (b, s_local, h, d)
        b, s_local, h, _ = q.shape
        my_idx = jax.lax.axis_index(axis)
        q_off = my_idx * s_local
        m = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
        l = jnp.zeros((b, h, s_local), jnp.float32)
        o = jnp.zeros(q.shape[:1] + (s_local,) + q.shape[2:], jnp.float32)
        perm = [(i, (i + 1) % p_size) for i in range(p_size)]

        def body(step, carry):
            m, l, o, k_blk, v_blk = carry
            # the block currently held originated at device (my_idx - step) mod P
            src = (my_idx - step) % p_size
            k_off = src * s_local
            if causal:
                # skip blocks strictly in our future (their mask would zero all)
                do_compute = src <= my_idx
            else:
                do_compute = True

            def compute(args):
                m, l, o = args
                return _block_attn_accum(q, k_blk, v_blk, q_off, k_off, m, l, o, causal, scale_)

            if causal:
                m, l, o = jax.lax.cond(do_compute, compute, lambda a: a, (m, l, o))
            else:
                m, l, o = compute((m, l, o))
            k_nxt = jax.lax.ppermute(k_blk, axis, perm)
            v_nxt = jax.lax.ppermute(v_blk, axis, perm)
            return m, l, o, k_nxt, v_nxt

        m, l, o, _, _ = jax.lax.fori_loop(0, p_size, body, (m, l, o, k, v))
        out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
        return out.astype(q.dtype)

    return shard_map(
        local_fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        **SHARD_MAP_UNCHECKED,
    )(q, k, v)
