"""Gradient/model compression operators (FedSGD path).

Parity with the reference's ``ml/utils/compression.py``: ``TopKCompressor:21``,
``EFTopKCompressor:139`` (error-feedback residuals), ``QuantizationCompressor:175``
(naive level quantization), ``QSGDCompressor:210`` (norm-scaled stochastic
quantization).  The reference compresses per-tensor with torch ops on the host;
here each operator is a pure JAX function over the flat parameter vector so it
fuses into the round program, and EF residuals are explicit state (threaded as
the client state of the FedSGD algorithm) rather than a stateful object.

Note: on-device "compression" keeps dense shapes (a masked vector), which is
the right semantics for simulation — the statistical effect is identical,
while the wire-level sparse encoding lives in ``comm.wire`` for real
cross-silo transport.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def top_k_mask(vec: jax.Array, ratio: float) -> jax.Array:
    """Keep the k = ceil(ratio * n) largest-|.| entries; zero the rest."""
    n = vec.shape[0]
    k = max(1, int(ratio * n))
    thresh = jax.lax.top_k(jnp.abs(vec), k)[0][-1]
    return jnp.where(jnp.abs(vec) >= thresh, vec, 0.0)


def ef_top_k(vec: jax.Array, residual: jax.Array, ratio: float):
    """Error-feedback TopK (EFTopKCompressor:139): add residual, compress,
    keep what was dropped as the next residual."""
    corrected = vec + residual
    compressed = top_k_mask(corrected, ratio)
    new_residual = corrected - compressed
    return compressed, new_residual


def quantize_naive(vec: jax.Array, levels: int = 256) -> jax.Array:
    """Uniform quantization to ``levels`` steps of the per-vector range
    (QuantizationCompressor semantics)."""
    vmax = jnp.max(jnp.abs(vec)) + 1e-12
    step = 2.0 * vmax / (levels - 1)
    return jnp.round(vec / step) * step


def qsgd(vec: jax.Array, key: jax.Array, levels: int = 256) -> jax.Array:
    """QSGD stochastic quantization (QSGDCompressor:210): scale by the l2
    norm, stochastically round to ``levels`` buckets — unbiased."""
    norm = jnp.linalg.norm(vec) + 1e-12
    scaled = jnp.abs(vec) / norm * levels
    floor = jnp.floor(scaled)
    prob = scaled - floor
    rnd = jax.random.uniform(key, vec.shape)
    q = floor + (rnd < prob).astype(vec.dtype)
    return jnp.sign(vec) * q * norm / levels


def qsgd_int8_fused(vec: jax.Array, key: jax.Array, interpret: bool = False) -> jax.Array:
    """Block-scaled stochastic int8 quantize+dequantize via the Pallas TPU
    kernel (``ops/pallas/quantize.py``) — the fused fast path for the QSGD
    semantics (one HBM read + int8 write instead of materialized f32
    intermediates).  ``interpret=True`` for CPU/CI."""
    from .pallas import qsgd_int8

    return qsgd_int8(vec, key, interpret=interpret)


def compress(name: str, vec: jax.Array, *, key: Optional[jax.Array] = None,
             residual: Optional[jax.Array] = None, ratio: float = 0.01,
             quantize_level: int = 8):
    """Dispatch matching reference ``compression`` config values
    (``no | topk | eftopk | quantize | qsgd``), plus ``qsgd_int8`` — the
    Pallas-fused block-scaled int8 fast path.  Returns (vec, new_residual)."""
    if name in ("no", "", None):
        return vec, residual
    if name == "topk":
        return top_k_mask(vec, ratio), residual
    if name == "eftopk":
        return ef_top_k(vec, residual, ratio)
    if name == "quantize":
        return quantize_naive(vec, 2 ** quantize_level), residual
    if name == "qsgd":
        return qsgd(vec, key, 2 ** quantize_level), residual
    if name == "qsgd_int8":
        import jax as _jax

        # the pallas interpreter is required off-TPU (CPU CI)
        return qsgd_int8_fused(vec, key, interpret=_jax.default_backend() != "tpu"), residual
    raise ValueError(f"unknown compression {name!r}")
