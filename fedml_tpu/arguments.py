"""Typed configuration with reference-YAML compatibility.

The reference merges sectioned YAML (``common_args / data_args / model_args /
train_args / validation_args / device_args / comm_args / tracking_args``) flat
onto a duck-typed ``args`` namespace (``python/fedml/arguments.py:36-193``,
``Arguments.__init__``/``set_attr_from_config``), and everything downstream
does ``hasattr`` probing.  Here the same YAML vocabulary loads into one typed
frozen-ish dataclass (``Config``) with explicit defaults, so mistyped recipe
keys fail loudly instead of silently defaulting — while any reference
``fedml_config.yaml`` for a supported feature parses unchanged.
"""

from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass, field
from typing import Any, Optional

import yaml

from . import constants


@dataclass
class Config:
    # ---- common_args -------------------------------------------------------
    training_type: str = constants.TRAINING_PLATFORM_SIMULATION
    random_seed: int = 0
    federated_optimizer: str = constants.FEDERATED_OPTIMIZER_FEDAVG
    scenario: str = "horizontal"
    config_version: str = "release"
    run_id: str = "0"
    using_mlops: bool = False

    # ---- data_args ---------------------------------------------------------
    dataset: str = "cifar10"
    data_cache_dir: str = "~/fedml_data"
    partition_method: str = "hetero"  # homo | hetero | hetero-fix
    partition_alpha: float = 0.5
    # TPU-native additions
    synthetic_fallback: bool = True  # generate deterministic data if files absent
    synthetic_train_size: int = 0  # 0 -> dataset default
    synthetic_test_size: int = 0

    # ---- model_args --------------------------------------------------------
    model: str = "resnet20"
    model_file_cache_folder: str = ""
    global_model_file_path: str = ""
    norm: str = "batch"  # batch | group (resnet_gn escape hatch, SURVEY §7.3)

    # ---- train_args --------------------------------------------------------
    client_num_in_total: int = 10
    client_num_per_round: int = 5
    comm_round: int = 10
    epochs: int = 1
    batch_size: int = 32
    client_optimizer: str = "sgd"
    learning_rate: float = 0.03
    momentum: float = 0.0
    weight_decay: float = 0.0
    server_optimizer: str = "sgd"  # for FedOpt / FedAvgM
    server_lr: float = 1.0
    server_momentum: float = 0.0
    # algorithm-specific knobs
    fedprox_mu: float = 0.1
    feddyn_alpha: float = 0.01
    fednova_tau_eff: str = "uniform"
    mime_momentum: float = 0.9
    async_staleness_alpha: float = 0.5  # mixing weight for Async_FedAvg
    async_staleness_func: str = "polynomial"  # constant | polynomial | hinge
    group_num: int = 1  # HierarchicalFL groups
    group_comm_round: int = 1  # sub-rounds per group before global agg
    # compression (FedSGD path, reference utils/compression.py)
    compression: str = "no"  # no | topk | eftopk | quantize | qsgd
    compression_ratio: float = 0.01
    quantize_level: int = 8
    is_biased: bool = False

    # ---- agg_args (fork research: MyAvg CKA layer-selective aggregation,
    # reference my_research/.../fedml_config_7_m5top3_opt.yaml agg_args) ----
    agg_unselect_layer: tuple = ()
    agg_all_select_layer: tuple = ()
    agg_any_select_layer: tuple = ()
    agg_mod_list: tuple = ()
    agg_mod_dict: dict = field(default_factory=dict)
    cka_select_topk: int = 3
    cka_unselect_layer: tuple = ()
    cka_all_select_layer: tuple = ()
    cka_any_select_layer: tuple = ()
    cka_low_thresh: float = 0.0
    cka_high_thresh: float = 1.0

    # ---- validation_args ---------------------------------------------------
    frequency_of_the_test: int = 5
    test_batch_size: int = 0  # 0 -> batch_size

    # ---- device_args -------------------------------------------------------
    using_gpu: bool = True  # kept for YAML parity; means "use accelerator"
    device_type: str = "tpu"
    mesh_shape: str = ""  # e.g. "clients:8" or "silo:2,data:4"; "" -> auto
    backend_sim: str = constants.SIMULATION_BACKEND_MESH  # sp | MESH
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"  # MXU-friendly local-train compute
    step_mode: str = "match"  # match reference per-client step counts | fixed

    # ---- comm_args ---------------------------------------------------------
    backend: str = constants.SIMULATION_BACKEND_MESH
    mqtt_config_path: str = ""
    s3_config_path: str = ""
    grpc_ipconfig_path: str = ""
    trpc_master_config_path: str = ""

    # ---- tracking_args -----------------------------------------------------
    log_file_dir: str = "./log"
    enable_wandb: bool = False
    metrics_jsonl_path: str = ""  # TPU-native: jsonl metrics sink
    enable_tracking: bool = True

    # ---- attack/defense/dp/secagg (reference security yaml sections) -------
    enable_attack: bool = False
    attack_type: str = ""
    attack_client_num: int = 0
    poisoned_client_list: tuple = ()
    enable_defense: bool = False
    defense_type: str = ""
    byzantine_client_num: int = 0
    krum_param_m: int = 1
    norm_bound: float = 5.0
    trimmed_mean_beta: float = 0.1
    outlier_detection_k: float = 3.0
    enable_dp: bool = False
    mechanism_type: str = "gaussian"  # gaussian | laplace
    dp_solution_type: str = "ldp"  # ldp | cdp | nbafl
    epsilon: float = 1.0
    delta: float = 1e-5
    sensitivity: float = 1.0
    clipping_norm: float = 1.0
    enable_secagg: bool = False
    secagg_prime_bits: int = 31
    secagg_quant_bits: int = 16
    enable_fhe: bool = False
    enable_contribution: bool = False
    contribution_method: str = "gtg_shapley"  # gtg_shapley | leave_one_out

    # ---- cross-silo / distributed ------------------------------------------
    rank: int = 0
    role: str = "server"
    worker_num: int = 0
    n_node_in_silo: int = 1
    n_proc_per_node: int = 1
    process_id: int = 0

    # ---- checkpoint (TPU-native first-class, SURVEY §5) --------------------
    checkpoint_dir: str = ""
    checkpoint_every_rounds: int = 0  # 0 -> disabled
    resume: bool = False

    # escape hatch for unknown/extra recipe keys (kept, warned once)
    extra: dict = field(default_factory=dict)

    def __post_init__(self):
        if self.test_batch_size == 0:
            self.test_batch_size = self.batch_size
        if isinstance(self.poisoned_client_list, list):
            self.poisoned_client_list = tuple(self.poisoned_client_list)
        for name in ("agg_unselect_layer", "agg_all_select_layer", "agg_any_select_layer",
                     "agg_mod_list", "cka_unselect_layer", "cka_all_select_layer",
                     "cka_any_select_layer"):
            v = getattr(self, name)
            if isinstance(v, list):
                object.__setattr__(self, name, tuple(v))

    # reference code reads duck-typed attributes; keep that working for extras
    def __getattr__(self, name: str) -> Any:  # graftlint: disable=GL001(the dynamic extra fallback cfg_extra builds on)
        extra = object.__getattribute__(self, "__dict__").get("extra", {})
        if name in extra:
            return extra[name]
        raise AttributeError(name)


_FIELD_NAMES = {f.name for f in dataclasses.fields(Config)}

# Reference key -> Config key renames (kept minimal; most names match).
_ALIASES = {
    "client_id_list": None,  # synthesized, ignored
    "using_gpu": "using_gpu",
    "gpu_id": None,
    "gpu_mapping_file": None,
    "gpu_mapping_key": None,
    "worker_num": "worker_num",
    "wandb_key": None,
    "wandb_project": None,
    "wandb_name": None,
}


def load_yaml_config(path: str) -> dict:
    with open(path, "r") as f:
        return yaml.safe_load(f) or {}


def config_from_sections(sections: dict) -> Config:
    """Flatten reference-style sectioned YAML into a Config."""
    flat: dict[str, Any] = {}
    for section, kv in sections.items():
        if not isinstance(kv, dict):
            flat[section] = kv
            continue
        for k, v in kv.items():
            flat[k] = v
    kwargs: dict[str, Any] = {}
    extra: dict[str, Any] = {}
    for k, v in flat.items():
        if k in _ALIASES and _ALIASES[k] is None:
            continue
        k = _ALIASES.get(k, k)
        if k == "extra" and isinstance(v, dict):
            # a literal `extra:` block in any section holds free-form knobs —
            # MERGE its contents (the old behavior nested it as
            # cfg.extra['extra'], silently disabling every documented knob)
            extra.update(v)
        elif k in _FIELD_NAMES and k != "extra":
            kwargs[k] = v
        else:
            extra[k] = v
    cfg = Config(**kwargs, extra=extra)
    return cfg


def add_args(argv: Optional[list[str]] = None) -> Config:
    """CLI entry mirroring reference ``add_args`` (``arguments.py:36``):
    ``--cf`` YAML config file, ``--rank``, ``--role``, ``--run_id`` overrides."""
    parser = argparse.ArgumentParser(prog="fedml_tpu")
    parser.add_argument("--cf", "--config_file", dest="cf", type=str, default=None)
    parser.add_argument("--rank", type=int, default=None)
    parser.add_argument("--role", type=str, default=None)
    parser.add_argument("--run_id", type=str, default=None)
    parser.add_argument("--run_device_id", type=str, default=None)
    ns, _unknown = parser.parse_known_args(argv)
    sections = load_yaml_config(ns.cf) if ns.cf else {}
    cfg = config_from_sections(sections)
    for k in ("rank", "role", "run_id"):
        v = getattr(ns, k)
        if v is not None:
            setattr(cfg, k, v)
    return cfg


def load_arguments(argv: Optional[list[str]] = None) -> Config:
    """Alias matching the reference entrypoint name (``arguments.py:193``)."""
    return add_args(argv)
