"""fedml_tpu — a TPU-native federated / distributed ML framework.

Re-designed from scratch for JAX/XLA/pjit (capability reference: FedML —
see SURVEY.md).  Top-level API mirrors the reference's
(``python/fedml/__init__.py``): ``init()``, ``run_simulation()``, plus the
typed ``Config`` replacing the duck-typed args namespace.
"""

from __future__ import annotations

import logging
from typing import Optional

__version__ = "0.1.0"

from . import constants  # noqa: E402
from .arguments import Config, add_args, load_arguments  # noqa: E402


def init(args: Optional[Config] = None, argv=None) -> Config:
    """Bootstrap: parse args/YAML, seed host RNGs, set up logging.

    Reference: ``fedml.init`` (``python/fedml/__init__.py:64``) — env-version
    resolution, seeding, per-platform arg mangling.  The TPU build needs no
    spawn-mode multiprocessing or MPI rank discovery for simulation (the mesh
    replaces worker processes); cross-silo rank/role come from the Config.
    """
    from .core import rng

    cfg = args if args is not None else add_args(argv)
    rng.seed_everything(cfg.random_seed)
    logging.basicConfig(
        level=logging.INFO,
        format="[fedml_tpu] %(asctime)s %(levelname)s %(message)s",
    )
    # MULTIPROCESS/MPI backend: bring up jax.distributed before any backend
    # use so the mesh spans all hosts (reference: MPI rank discovery in
    # fedml.init; here the coordination service replaces mpi4py).
    from .parallel import multihost

    requested = getattr(cfg, "backend_sim", "") in (
        "MULTIPROCESS", constants.SIMULATION_BACKEND_MPI,
    )
    from .core.flags import cfg_extra

    if requested or cfg_extra(cfg, "coordinator_address"):
        up = multihost.ensure_initialized(cfg)
        if requested and not up:
            # an explicitly requested multi-process backend must never
            # silently degrade to single-process (the other hosts would block
            # forever in the coordination barrier)
            raise ValueError(
                "backend_sim=MULTIPROCESS requires coordinator config: set "
                "cfg.extra coordinator_address/num_processes/process_id or "
                "the JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES/JAX_PROCESS_ID "
                "environment variables on every host"
            )
    return cfg


def run_simulation(cfg: Optional[Config] = None, backend: Optional[str] = None):
    """One-line simulation entry (reference ``launch_simulation.py:9``)."""
    from .runner import FedMLRunner

    cfg = init(cfg)
    if backend:
        cfg.backend_sim = backend
    runner = FedMLRunner(cfg)
    return runner.run()


def run_cross_silo_server(cfg: Optional[Config] = None):
    """Reference ``launch_cross_silo_horizontal.py:7``."""
    from .runner import FedMLRunner

    cfg = init(cfg)
    cfg.training_type = constants.TRAINING_PLATFORM_CROSS_SILO
    cfg.role = "server"
    return FedMLRunner(cfg).run()


def run_cross_silo_client(cfg: Optional[Config] = None):
    """Reference ``launch_cross_silo_horizontal.py:28``."""
    from .runner import FedMLRunner

    cfg = init(cfg)
    cfg.training_type = constants.TRAINING_PLATFORM_CROSS_SILO
    cfg.role = "client"
    return FedMLRunner(cfg).run()
