"""Round-level checkpoint / resume.

The reference has no round-level checkpointing in the core FL loop (SURVEY.md
§5: only final model artifacts to S3; the LLM path leans on HF Trainer).
Here (round_idx, global variables, server state, client states, RNG key) is a
first-class checkpoint via orbax — so a 10k-round run survives preemption,
which is table stakes on TPU pods.
"""

from __future__ import annotations

import logging
import os
import shutil
from pathlib import Path
from typing import Any, Optional

import jax
import numpy as np

from ..analysis import tracesan

log = logging.getLogger("fedml_tpu.core.checkpoint")


class RoundCheckpointer:
    def __init__(self, directory: str, keep: int = 3):
        # orbax import is deferred to first USE: the simulators import this
        # module unconditionally, but orbax is only needed when a
        # checkpoint_dir is actually configured
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        options = ocp.CheckpointManagerOptions(max_to_keep=keep, create=True)
        self.mngr = ocp.CheckpointManager(str(self.directory), options=options)

    def save(self, round_idx: int, state: dict) -> None:
        """state: pytree dict (global_vars, server_state, client_states, key...)."""
        with tracesan.allow("checkpoint"):
            state = jax.device_get(state)
        try:
            self.mngr.save(round_idx, args=self._ocp.args.StandardSave(state))
        except ValueError:
            # Two managers over one directory (a lingering pre-crash writer's
            # retention GC racing the restarted server): the other writer can
            # delete a step this manager still has cached, which fails save()'s
            # old-step bookkeeping AFTER the write itself was initiated.
            # Re-sync the cached step list with the directory and retry; when
            # the initiated write already committed in the background, the
            # step is on disk and the retry is skipped.
            self.mngr.wait_until_finished()
            self.mngr.reload()
            if round_idx not in set(self.mngr.all_steps()):
                self.mngr.save(round_idx, args=self._ocp.args.StandardSave(state))
        self.mngr.wait_until_finished()

    def _step_intact(self, step: int) -> bool:
        """Integrity probe of one step: every array/metadata file orbax
        committed must still be readable.  A crash can leave the LATEST step
        truncated (the commit marker landed but a tensor file did not flush
        fully on a hard kill) — mirroring the AOT store's corrupt-entry
        semantics, such a step is discarded rather than served.  The probe
        restores with template-less StandardRestore args: a FRESH manager
        (the recovery case) has no handler registered for a bare restore."""
        try:
            self.mngr.restore(step, args=self._ocp.args.StandardRestore())
            return True
        except Exception as e:  # orbax raises transport-specific types
            log.warning("checkpoint step %s under %s is unreadable (%s: %s) — "
                        "discarding and falling back to the previous step",
                        step, self.directory, type(e).__name__, e)
            return False

    def _discard_step(self, step: int) -> None:
        for name in (str(step), f"{step}"):
            p = self.directory / name
            if p.exists():
                shutil.rmtree(p, ignore_errors=True)
        try:
            self.mngr.reload()
        except Exception:
            pass

    def latest_round(self) -> Optional[int]:
        """Newest INTACT step (corrupt/partial steps are discarded so a
        truncated latest checkpoint falls back to the previous good one)."""
        steps = sorted(self.mngr.all_steps(), reverse=True)
        for step in steps:
            if self._step_intact(step):
                return step
            self._discard_step(step)
        return None

    def restore(self, round_idx: Optional[int] = None, template: Optional[dict] = None) -> dict:
        step = round_idx if round_idx is not None else self.latest_round()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        if template is not None:
            template = jax.device_get(template)
            return self.mngr.restore(step, args=self._ocp.args.StandardRestore(template))
        return self.mngr.restore(step)

    def close(self) -> None:
        self.mngr.close()


class RoundCheckpointMixin:
    """Shared round-level save/resume plumbing for simulators.

    A simulator mixes this in and defines:
    - ``_ckpt_state() -> dict`` — the round-resumable state pytree (also the
      restore template), and
    - ``_apply_ckpt_state(state) -> None`` — install a restored state
      (placement/sharding concerns live here, e.g. the mesh engine re-applies
      device placement; key arrays are authoritative over config seeds).
    Requires ``self.cfg`` (checkpoint_dir/resume) and ``self.round_idx``.
    """

    def _checkpointer(self) -> "RoundCheckpointer":
        if getattr(self, "_ckpt", None) is None:
            self._ckpt = RoundCheckpointer(self.cfg.checkpoint_dir)
        return self._ckpt

    def save_checkpoint(self) -> None:
        if not self.cfg.checkpoint_dir:
            return
        self._checkpointer().save(self.round_idx, self._ckpt_state())

    def try_resume(self) -> bool:
        if not (self.cfg.checkpoint_dir and getattr(self.cfg, "resume", False)):
            return False
        if self._checkpointer().latest_round() is None:
            return False
        state = self._ckpt.restore(template=self._ckpt_state())
        self._apply_ckpt_state(state)
        return True

    def maybe_save_checkpoint(self, completed_round: int) -> None:
        """Save when the cadence says so: every ``checkpoint_every_rounds``
        completed rounds and at the final round (one cadence definition for
        every simulator)."""
        every = getattr(self.cfg, "checkpoint_every_rounds", 0)
        if every and (
            (completed_round + 1) % every == 0
            or completed_round == self.cfg.comm_round - 1
        ):
            self.save_checkpoint()
