"""Pytree parameter utilities.

The reference manipulates ``OrderedDict`` torch state_dicts with explicit
python loops (e.g. the weighted-average loop in
``simulation/sp/fedavg/fedavg_api.py:144-159`` and the per-optimizer branches
of ``ml/aggregator/agg_operator.py:33-135``).  Here model/optimizer state is a
JAX pytree and every one of those loops becomes a single ``jax.tree_util.tree_map``
— which XLA fuses into a handful of elementwise kernels on device.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

Pytree = Any


def tree_zeros_like(tree: Pytree) -> Pytree:
    return jax.tree_util.tree_map(jnp.zeros_like, tree)


def tree_add(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree_util.tree_map(jnp.add, a, b)


def tree_sub(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree_util.tree_map(jnp.subtract, a, b)


def tree_scale(tree: Pytree, s) -> Pytree:
    return jax.tree_util.tree_map(lambda x: x * s, tree)


def tree_axpy(a, x: Pytree, y: Pytree) -> Pytree:
    """a * x + y, elementwise over the tree."""
    return jax.tree_util.tree_map(lambda xi, yi: a * xi + yi, x, y)


def tree_dot(a: Pytree, b: Pytree) -> jax.Array:
    """Inner product over all leaves (f32 accumulation)."""
    leaves = jax.tree_util.tree_map(
        lambda x, y: jnp.vdot(x.astype(jnp.float32), y.astype(jnp.float32)), a, b
    )
    return jax.tree_util.tree_reduce(jnp.add, leaves, jnp.float32(0.0))


def tree_sq_norm(tree: Pytree) -> jax.Array:
    return tree_dot(tree, tree)


def tree_norm(tree: Pytree) -> jax.Array:
    return jnp.sqrt(tree_sq_norm(tree))


def tree_size(tree: Pytree) -> int:
    """Total number of scalar parameters (static python int)."""
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(tree))


def tree_cast(tree: Pytree, dtype) -> Pytree:
    return jax.tree_util.tree_map(lambda x: x.astype(dtype), tree)


def tree_weighted_mean(stacked: Pytree, weights: jax.Array) -> Pytree:
    """Weighted mean over a leading "clients" axis.

    ``stacked`` has leaves of shape ``(n, *leaf_shape)``; ``weights`` is
    ``(n,)`` and is normalised internally.  This is the TPU-native form of the
    reference's ``_aggregate`` loop (``fedavg_api.py:144-159``): one fused
    reduction instead of a python loop over parameter keys and clients.
    """
    w = weights.astype(jnp.float32)
    w = w / jnp.maximum(jnp.sum(w), 1e-12)

    def avg(leaf):
        wb = w.reshape((-1,) + (1,) * (leaf.ndim - 1))
        return jnp.sum(leaf.astype(jnp.float32) * wb, axis=0).astype(leaf.dtype)

    return jax.tree_util.tree_map(avg, stacked)


def tree_stack(trees: Sequence[Pytree]) -> Pytree:
    """Stack a list of identically-structured trees along a new leading axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0), *trees)


def tree_unstack(stacked: Pytree, n: int) -> list[Pytree]:
    return [jax.tree_util.tree_map(lambda x: x[i], stacked) for i in range(n)]


def tree_take(stacked: Pytree, idx: jax.Array) -> Pytree:
    """Gather a subset of the leading axis (client-sampling primitive).

    Device-side gather so per-round client sampling does not retrace
    (SURVEY.md §7 hard part 2).
    """
    return jax.tree_util.tree_map(lambda x: jnp.take(x, idx, axis=0), stacked)


def tree_flatten_to_vector(tree: Pytree) -> tuple[jax.Array, Callable[[jax.Array], Pytree]]:
    """Flatten a pytree into one f32 vector + an unravel closure.

    Wire-format and defense primitives (Krum distances, norm clipping) operate
    on flat vectors; this is the pytree analogue of the reference's
    ``vectorize_weight`` helpers in ``core/security/defense/defense_base.py``.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = [l.shape for l in leaves]
    sizes = [int(l.size) for l in leaves]
    dtypes = [l.dtype for l in leaves]
    flat = jnp.concatenate([l.reshape(-1).astype(jnp.float32) for l in leaves]) if leaves else jnp.zeros((0,), jnp.float32)

    def unravel(vec: jax.Array) -> Pytree:
        out = []
        offset = 0
        for shape, size, dtype in zip(shapes, sizes, dtypes):
            out.append(vec[offset : offset + size].reshape(shape).astype(dtype))
            offset += size
        return jax.tree_util.tree_unflatten(treedef, out)

    return flat, unravel


def stacked_tree_to_matrix(stacked: Pytree) -> jax.Array:
    """(n, *) stacked client trees -> (n, d) f32 matrix (for defenses)."""
    leaves = jax.tree_util.tree_leaves(stacked)
    n = leaves[0].shape[0]
    return jnp.concatenate([l.reshape(n, -1).astype(jnp.float32) for l in leaves], axis=1)


def matrix_to_stacked_tree(mat: jax.Array, template_stacked: Pytree) -> Pytree:
    """Inverse of :func:`stacked_tree_to_matrix` using a stacked template."""
    leaves, treedef = jax.tree_util.tree_flatten(template_stacked)
    n = mat.shape[0]
    out = []
    offset = 0
    for l in leaves:
        size = int(l.size // n)
        out.append(mat[:, offset : offset + size].reshape(l.shape).astype(l.dtype))
        offset += size
    return jax.tree_util.tree_unflatten(treedef, out)
