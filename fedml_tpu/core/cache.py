"""Shared persistent XLA compilation-cache setup.

One helper, three callers: ``tests/conftest.py`` (the tier-1 suite is
dominated by XLA compiles), the ``__graft_entry__`` multichip dryrun (whose
~7 sharded programs previously compiled cold in the re-exec'd child every
run — the rc=124 driver timeout), and ``bench.py`` (warm re-runs of the A/B
benches).  All three share ONE on-disk cache at the repo root, so a dryrun
re-run or a bench after the test suite starts warm.

The cache directory is keyed per host CPU fingerprint: XLA:CPU AOT entries
compiled on a host with different machine features load with "could lead to
SIGILL" warnings and occasionally abort the process mid-suite (observed:
``Fatal Python error: Aborted`` inside a jitted round) — a cache written on
another machine must never be read.  TPU entries key on the device kind via
XLA's own cache key, so chip and CPU entries coexist in one directory.
"""

from __future__ import annotations

import hashlib
import os
import platform


def host_fingerprint() -> str:
    """Stable 12-hex digest of this host's CPU feature set (x86 ``flags``,
    aarch64 ``Features``, plus model identifiers)."""
    cpu_flags = platform.machine() + platform.processor()
    try:
        seen = set()
        with open("/proc/cpuinfo") as f:
            for line in f:
                # x86 says "flags", aarch64 says "Features"; model lines cover
                # hosts with neither.  First occurrence of each key (cpuinfo
                # repeats per core) — the feature list is the actual contract.
                key = line.split(":", 1)[0].strip()
                if key in ("flags", "Features", "model name", "CPU part") and key not in seen:
                    seen.add(key)
                    cpu_flags += line.strip()
    except OSError:
        pass
    return hashlib.sha1(cpu_flags.encode()).hexdigest()[:12]


def cache_dir(root: str | None = None) -> str:
    """``<root>/.jax_cache-<host_tag>``; root defaults to the repo checkout
    (the parent of the ``fedml_tpu`` package) — the same path
    ``tests/conftest.py`` has always used, so existing caches stay warm."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.abspath(os.path.join(root, f".jax_cache-{host_fingerprint()}"))


def setup_persistent_cache(root: str | None = None) -> str:
    """Point jax at the shared persistent compilation cache and return its
    path.  Call AFTER any platform/env forcing but before the first compile;
    idempotent."""
    import jax

    path = cache_dir(root)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return path
