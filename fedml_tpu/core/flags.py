"""Central registry for every ``cfg.extra`` feature flag + the one accessor.

``Config.extra`` is the escape hatch for recipe knobs that are not typed
dataclass fields — and before this registry it was read at ~40 sites with
two inconsistent idioms (``extra.get(...)`` on a local, inline
``(getattr(cfg, "extra", {}) or {}).get(...)``) and no inventory at all: a
typo'd recipe key silently fell back to its default, the main source of
silent cross-silo misconfiguration.  Now:

- every flag is declared ONCE here as a :class:`FlagSpec` (type, default,
  one-line doc);
- every read goes through :func:`cfg_extra`, which refuses undeclared names
  at runtime;
- the GL001 lint rule (``fedml_tpu/analysis/rules/gl001_flags.py``) enforces
  both directions statically: an undeclared read and a dead declaration are
  tier-1 failures;
- ``docs/FLAGS.md`` is generated from this registry
  (:func:`render_flag_reference`, ``python -m fedml_tpu.core.flags``).

``default=None`` with a ``derived:`` doc means the default is computed at
the call site (e.g. ``secagg_target_u`` defaults to ``t + 1``) — the caller
passes it explicitly to :func:`cfg_extra`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

__all__ = ["FlagSpec", "FLAGS", "cfg_extra", "cfg_extra_present",
           "set_cfg_extra", "render_flag_reference"]


@dataclass(frozen=True)
class FlagSpec:
    name: str
    type: str       # bool | int | float | str | dict | list
    default: Any    # None with a "derived:" doc = computed at the call site
    doc: str


_UNSET = object()


def _specs(*specs: FlagSpec) -> dict[str, FlagSpec]:
    out: dict[str, FlagSpec] = {}
    for s in specs:
        if s.name in out:
            raise ValueError(f"duplicate flag declaration {s.name!r}")
        out[s.name] = s
    return out


FLAGS: dict[str, FlagSpec] = _specs(
    # -- training / model ----------------------------------------------------
    FlagSpec("fused_blocks", "bool", False,
             "Route CIFAR-ResNet conv epilogues through the fused Pallas "
             "BasicBlock kernel (BN scale/shift + residual + ReLU in one pass)."),
    FlagSpec("mlp_hidden", "int", 128,
             "Hidden width of the synthetic `mlp` model (comm benches widen it "
             "past the compression block size)."),
    FlagSpec("silo_dp", "bool", True,
             "Intra-silo data parallelism over local devices when batch_size "
             "divides the local device count."),
    FlagSpec("unitedllm", "bool", False,
             "Cross-cloud runs exchange ONLY LoRA adapters (federated LLM "
             "training, UnitedLLM protocol)."),
    FlagSpec("lora_r", "int", None,
             "LoRA adapter rank; derived: surface default (8 FedLLM, 4 UnitedLLM)."),
    FlagSpec("lora_alpha", "float", 16.0, "LoRA scaling alpha."),
    FlagSpec("lora_targets", "list", None,
             "Module name substrings receiving LoRA adapters; derived: "
             "llm.lora.DEFAULT_TARGETS."),
    # -- simulator workloads -------------------------------------------------
    FlagSpec("seg_base", "int", 8, "UNet base channel width for FedSeg."),
    FlagSpec("gan_z_dim", "int", 64, "FedGAN generator latent dimension."),
    FlagSpec("decentralized_mode", "str", "dsgd",
             "Decentralized topology/algorithm: dsgd | ring."),
    FlagSpec("topology_neighbor_num", "int", 2,
             "Neighbors per node in the decentralized mixing topology."),
    FlagSpec("ta_group_num", "int", 4, "TurboAggregate group count."),
    FlagSpec("ta_dropout_prob", "float", 0.0,
             "TurboAggregate simulated per-client dropout probability."),
    FlagSpec("group_assignment", "str", "balanced",
             "HierarchicalFL client-to-group assignment: balanced | random."),
    FlagSpec("vfl_party_num", "int", 2, "Vertical-FL party count."),
    FlagSpec("vfl_embed_dim", "int", 16, "Vertical-FL per-party embedding dim."),
    FlagSpec("nas_cells", "int", 2, "FedNAS DARTS cell count."),
    FlagSpec("nas_features", "int", 16, "FedNAS DARTS feature width."),
    FlagSpec("nas_arch_lr", "float", 3e-3, "FedNAS architecture learning rate."),
    FlagSpec("condshift_clusters", "int", 2,
             "Conditional-shift synthetic partitioner: label cluster count."),
    FlagSpec("condshift_scale", "float", 0.9,
             "Conditional-shift synthetic partitioner: shift strength."),
    # -- population-scale simulation (fedml_tpu/population/) -----------------
    FlagSpec("population_store", "str", None,
             "Root directory of the sharded client-state store; set -> the "
             "MeshSimulator streams per-round cohorts from disk shards "
             "instead of holding the full client stack in memory (unset = "
             "the in-memory path, bit-identical to before the flag existed)."),
    FlagSpec("population_size", "int", None,
             "Simulated population client count; derived: dataset.n_clients. "
             "Ids beyond the base dataset replicate its client shards "
             "cyclically."),
    FlagSpec("population_shard_size", "int", 4096,
             "Clients per store shard (one npz file of contiguous ids)."),
    FlagSpec("population_max_resident_shards", "int", 8,
             "Bounded LRU of in-memory shards — the knob that caps host RSS."),
    FlagSpec("population_shards_per_cohort", "int", None,
             "Shards the hierarchical sampler prefers per cohort; derived: "
             "ceil(2 * cohort / shard_size)."),
    FlagSpec("population_prefetch", "bool", True,
             "Double-buffered cohort prefetch: gather round k+1's data on a "
             "worker thread while round k computes."),
    # -- ahead-of-time program store (fedml_tpu/core/aot.py) -----------------
    FlagSpec("aot_programs", "bool", False,
             "Persist jax.export-serialized round/eval programs in the "
             "on-disk program store so warm restarts skip re-tracing (the "
             "remaining XLA compile rides the persistent compilation cache); "
             "unset = the plain jit path, bit-identical to before the flag "
             "existed."),
    FlagSpec("aot_programs_dir", "str", None,
             "Program-store directory; derived: "
             "<repo>/.jax_cache-<host>/aot_programs (core/cache.py's dir)."),
    # -- communication / transports ------------------------------------------
    FlagSpec("comm_compression", "str", None,
             "Upload codec for cross-silo model replies: qsgd8 | topk "
             "(unset = raw wire v1, byte-identical to the uncompressed protocol)."),
    FlagSpec("comm_topk_ratio", "float", None,
             "top-k codec keep ratio; derived: cfg.compression_ratio (0.01)."),
    FlagSpec("comm_compress_min_size", "int", 1024,
             "Minimum leaf element count before a float leaf is compressed "
             "(block padding would EXPAND smaller leaves)."),
    FlagSpec("streaming_aggregation", "bool", False,
             "Fold arriving client updates into a running weighted sum even "
             "without a codec (peak buffered updates <= 2)."),
    FlagSpec("server_shard_fold", "bool", False,
             "Place the server's streaming-fold accumulator (and the "
             "finalized global it produces) under parallel/mesh "
             "NamedShardings: each arriving leaf is device_put to its shard "
             "owners and folded there under jit instead of host-gathered — "
             "bitwise the host fold (unset = the host numpy fold, "
             "bit-identical to before the flag existed)."),
    FlagSpec("comm_chunk_bytes", "int", 0,
             "Split gRPC/TCP/in-proc sends larger than this into bounded "
             "chunk frames that interleave at the socket level — BOTH legs: "
             "client uploads and the server->client model broadcast "
             "(receivers reassemble + decode incrementally per peer); 0 = "
             "one frame per message, byte-identical to the unchunked "
             "protocol."),
    FlagSpec("comm_chunk_idle_sweep_s", "float", 120.0,
             "Idle timeout for a partially assembled chunk stream: a sender "
             "that dies mid-upload has its stream evicted (a metered, "
             "sender-attributed drop) after this long without a new chunk."),
    # -- deterministic chaos injection (fedml_tpu/comm/chaos.py) --------------
    FlagSpec("chaos_seed", "int", 0,
             "Seed of the deterministic per-peer fault schedule; the same "
             "seed over the same message sequence reproduces the same "
             "faults exactly."),
    FlagSpec("chaos_drop_prob", "float", 0.0,
             "Per-send probability a message silently vanishes on the wire."),
    FlagSpec("chaos_delay_prob", "float", 0.0,
             "Per-send probability a message is delivered late (uniform in "
             "(0, chaos_delay_max_s])."),
    FlagSpec("chaos_delay_max_s", "float", 0.05,
             "Upper bound of an injected delivery delay."),
    FlagSpec("chaos_duplicate_prob", "float", 0.0,
             "Per-send probability a message is delivered twice (at-least-"
             "once transport redelivery)."),
    FlagSpec("chaos_reorder_prob", "float", 0.0,
             "Per-send probability a message is held back and delivered "
             "AFTER the next message to the same peer."),
    FlagSpec("chaos_corrupt_prob", "float", 0.0,
             "Per-send probability the encoded frame ships with flipped "
             "bytes (must die in the receive loop's drop path, never in a "
             "handler)."),
    FlagSpec("chaos_reset_prob", "float", 0.0,
             "Per-send probability the transport raises ConnectionResetError "
             "instead of sending (the peer-gone failure senders must survive)."),
    FlagSpec("chaos_partition", "str", None,
             "Timed network partition as 'start_s:duration_s' after comm-"
             "manager start: every send inside the window fails with "
             "ConnectionResetError (unset = no partition)."),
    FlagSpec("grpc_base_port", "int", 8890, "gRPC backend rank-0 port."),
    FlagSpec("grpc_ip_config", "dict", None,
             "gRPC backend rank -> host mapping (unset = localhost)."),
    FlagSpec("tcp_base_port", "int", 9690, "TCP backend rank-0 port."),
    FlagSpec("tcp_ip_config", "dict", None,
             "TCP backend rank -> host mapping (unset = localhost)."),
    FlagSpec("mqtt_host", "str", None,
             "Real MQTT broker host for the MQTT_S3 backend (unset = in-proc "
             "loopback broker)."),
    FlagSpec("mqtt_port", "int", 1883, "Real MQTT broker port."),
    FlagSpec("object_store_url", "str", None,
             "HTTP object store for >8KB MQTT payloads (required with mqtt_host)."),
    # -- cross-silo / cross-device server ------------------------------------
    FlagSpec("async_aggregation", "bool", False,
             "Buffered-async (FedBuff-style) cross-silo server: clients "
             "upload whenever local training finishes, arrivals fold into "
             "the streaming accumulator with staleness-decayed weights, and "
             "a virtual round closes every async_buffer_k arrivals (unset = "
             "the synchronous round server, bit-identical to before the "
             "flag existed)."),
    FlagSpec("async_buffer_k", "int", 8,
             "Arrivals folded per virtual round on the buffered-async "
             "server (FedBuff's K)."),
    FlagSpec("async_staleness_exponent", "float", 0.5,
             "Polynomial staleness decay s(tau) = (1 + tau)^-alpha applied "
             "to each async arrival's weight; 0 disables the decay."),
    FlagSpec("async_concurrency", "int", None,
             "Clients kept training concurrently by the async server; "
             "derived: client_num_per_round."),
    FlagSpec("async_redispatch_timeout_s", "float", 30.0,
             "Async dispatch deadline: an upload not back within this many "
             "seconds counts a health breach and the work is re-issued to "
             "another client; 0 disables the watchdog."),
    FlagSpec("server_journal_dir", "str", None,
             "Durable server recovery journal directory: the cross-silo "
             "servers (sync + buffered-async) atomically snapshot their full "
             "protocol state at round boundaries and recover from it on "
             "restart with a bumped session epoch (unset = no journal, "
             "wire + aggregation bit-identical to before the flag existed)."),
    FlagSpec("server_journal_keep", "int", 3,
             "Journal snapshots retained on disk (older steps are pruned; "
             "the newest intact step is never pruned)."),
    FlagSpec("server_journal_every_rounds", "int", 1,
             "Snapshot cadence in (virtual) rounds; the final round is "
             "always journaled."),
    FlagSpec("server_journal_every_folds", "int", 0,
             "MID-ROUND snapshot cadence on the synchronous server: with the "
             "streaming fold engaged, journal the partial accumulator every "
             "N folds so a crash between folds resumes the round's partial "
             "sum instead of redoing it (0 = round-boundary snapshots only; "
             "requires server_journal_dir)."),
    FlagSpec("client_journal_dir", "str", None,
             "Durable CLIENT recovery journal root: each cross-silo client "
             "atomically snapshots its protocol state (error-feedback "
             "residuals, last-received version + session epoch, upload "
             "idempotence attempts, optional trainer local state) before "
             "every upload and resumes mid-conversation from it on restart; "
             "uploads carry an idempotence key the servers dedup on (unset "
             "= no journal, no key header, wire byte-identical to before "
             "the flag existed)."),
    FlagSpec("client_journal_keep", "int", 2,
             "Client-journal snapshots retained per client (older steps are "
             "pruned)."),
    FlagSpec("client_journal_keep_retired", "int", 8,
             "Per-rank journal directories of RETIRED clients (ranks no "
             "longer in the live set) kept under client_journal_dir; older "
             "retired dirs are reclaimed at run finish — live ranks are "
             "never pruned."),
    # -- hierarchical aggregation tree (cross_silo/edge.py) -------------------
    FlagSpec("hier_fanout", "int", 0,
             "Children per aggregator in the hierarchical aggregation tree: "
             "set > 0 to route client uploads through ceil(N/fanout) edge "
             "aggregators that fold their children's arrivals and ship ONE "
             "pre-folded weighted partial to the root (0 = flat protocol, "
             "byte-identical to before the flag existed)."),
    FlagSpec("hier_depth", "int", 2,
             "Aggregation tree depth when hier_fanout is set: 2 = client -> "
             "edge -> root; 3 adds a region tier between edges and root."),
    FlagSpec("hier_topology", "dict", None,
             "Explicit aggregation tree: {'edges': [[client_rank, ...], ...]"
             ", 'regions': [[edge_ordinal, ...], ...]} — overrides the "
             "hier_fanout round-robin construction (regions optional; every "
             "client rank must appear in exactly one edge)."),
    FlagSpec("hier_hop_codec", "str", None,
             "Per-hop re-encode of the edge->parent partial: qsgd8 | topk "
             "(unset = the raw f32 partial, which keeps the tree fold "
             "bitwise equal to the flat streaming fold)."),
    FlagSpec("straggler_timeout_s", "float", 0.0,
             "Bounded-wait straggler deadline per round; 0 = wait forever."),
    FlagSpec("straggler_quorum_frac", "float", 0.5,
             "Fraction of selected clients that must arrive before a "
             "straggler-timeout round proceeds."),
    FlagSpec("health_aware_selection", "bool", False,
             "client_selection deprioritizes degraded ranks using the "
             "per-client health ledger."),
    FlagSpec("device_max_missed_rounds", "int", 2,
             "Cross-device liveness: rounds a device may miss before "
             "exclusion from candidate selection."),
    FlagSpec("cross_device_timeout_s", "float", 600.0,
             "Cross-device server run deadline."),
    # -- secure aggregation / crypto -----------------------------------------
    FlagSpec("secagg_method", "str", "lightsecagg",
             "Secure-aggregation protocol: lightsecagg | shamir."),
    FlagSpec("secagg_privacy_t", "int", None,
             "Secret-sharing privacy threshold; derived: max(1, n_clients // 2)."),
    FlagSpec("secagg_target_u", "int", None,
             "LightSecAgg surviving-client target; derived: privacy_t + 1."),
    FlagSpec("secagg_q_bits", "int", 16, "Secure-aggregation quantization bits."),
    FlagSpec("secagg_stream", "bool", False,
             "Streaming secure aggregation (ISSUE 15): masked uploads fold "
             "one at a time into a running field total (peak buffered <= 2 "
             "at any cohort size) and ship on the minimal ring dtype "
             "(dense+mask u32 instead of int64; qsgd8+mask at int8 width + "
             "cohort carry bits); dropout masks reconstructed and "
             "subtracted once at finalize.  Unset = the historical "
             "buffer-all protocol, wire byte-identical."),
    FlagSpec("secagg_q8_frac_bits", "int", 7,
             "Fractional bits of the quantize-then-mask int8 grid "
             "(comm_compression=qsgd8 under secagg_stream): deltas quantize "
             "to round(x * 2^bits) stochastically, clipped to [-127, 127]. "
             "A CONFIG-SHARED scale — per-block adaptive qsgd8 scales "
             "cannot decode a masked sum."),
    FlagSpec("fhe_key_seed", "int", None,
             "RLWE key seed (out-of-band in production); derived: "
             "random_seed * 7919 + 17."),
    FlagSpec("fhe_ring_dim", "int", 1024, "RLWE ring dimension."),
    FlagSpec("fhe_frac_bits", "int", 16, "FHE fixed-point fractional bits."),
    # -- trust: attacks / defenses -------------------------------------------
    FlagSpec("attack_boost", "float", 10.0, "Model-replacement attack boost."),
    FlagSpec("attack_original_class", "int", 0, "Backdoor source class."),
    FlagSpec("attack_target_class", "int", 1, "Backdoor target class."),
    FlagSpec("attack_poison_frac", "float", 0.5,
             "Fraction of an attacker's shard that is poisoned."),
    FlagSpec("edge_case_type", "str", "southwest",
             "Edge-case backdoor variant (reference attack zoo name)."),
    FlagSpec("soteria_percentile", "float", 1.0,
             "Soteria defense: percentile of elements perturbed."),
    FlagSpec("wbc_pert_strength", "float", 1.0, "WBC defense perturbation strength."),
    FlagSpec("wbc_lr", "float", 0.1, "WBC defense inner learning rate."),
    # -- observability -------------------------------------------------------
    FlagSpec("metrics_port", "int", None,
             "Serve /metrics + /healthz on this port (unset = no server)."),
    FlagSpec("otlp_endpoint", "str", None,
             "OTLP/HTTP collector base URL; unset = no exporter object, no "
             "worker thread ($FEDML_TPU_OTLP_ENDPOINT overrides)."),
    FlagSpec("enable_remote_obs", "bool", False,
             "Clients ship telemetry batches to the server's ObsCollector "
             "over the FL transport."),
    FlagSpec("obs_jsonl_path", "str", None,
             "Server-side collector JSONL trail path (obs report input)."),
    FlagSpec("otlp_protocol", "str", "json",
             "OTLP/HTTP encoding: json (proto3-JSON, the default), protobuf "
             "(stdlib binary proto writer), or auto (start JSON, fall back "
             "to protobuf for the rest of the run when the collector "
             "rejects the JSON body with 415/400)."),
    FlagSpec("flight_recorder", "bool", False,
             "Per-process flight recorder: a bounded ring of recent spans, "
             "metric deltas, comm/chaos events, and journal/epoch "
             "transitions that dumps an atomic black-box bundle on trigger "
             "(unhandled exception, SIGTERM, SLO breach, accounting "
             "violation, hard kill, finish); unset = no ring, no taps, no "
             "bundles — the default path is bit-identical to before the "
             "flag existed."),
    FlagSpec("flight_dir", "str", None,
             "Directory black-box bundles are dumped into; derived: "
             "<cwd>/flight_bundles."),
    FlagSpec("flight_capacity", "int", 4096,
             "Flight-recorder ring capacity in events (oldest evicted "
             "first — the bound that keeps black-box memory constant under "
             "sustained load)."),
    FlagSpec("flight_window_s", "float", 60.0,
             "Seconds of ring history a bundle includes (0 = everything "
             "still in the ring)."),
    FlagSpec("slo_specs", "dict", None,
             "Declarative SLO specs evaluated on registry snapshots via the "
             "server runtime's timer wheel: {name: {metric, stat, op, "
             "threshold[, per][, labels]}} — stat is value|sum|count|rate|"
             "mean|pNN; breaches land in the collector trail, OTLP, and "
             "fedml_slo_breaches_total{slo} (unset = no engine, no timer)."),
    FlagSpec("slo_interval_s", "float", 1.0,
             "SLO evaluation cadence on the timer wheel."),
    FlagSpec("slo_flight_dump", "bool", False,
             "An SLO breach additionally triggers a flight-recorder bundle "
             "dump (once per SLO, requires flight_recorder)."),
    FlagSpec("cost_model_gauges", "bool", False,
             "Run XLA cost_analysis() on AOT-store programs at build/load "
             "and export fedml_program_flops / "
             "fedml_program_bytes_accessed gauges per program, plus the "
             "derived per-round achieved-FLOPS/MFU gauges in sim/engine.py "
             "(forces an eager compile at program resolve time; unset = no "
             "cost analysis, bit-identical default path)."),
    FlagSpec("perf_timeline", "bool", False,
             "Continuous performance timeline: periodic registry-snapshot "
             "deltas sampled on the server runtime's timer wheel into a "
             "bounded in-memory ring plus atomic on-disk segment files, "
             "with range-scan / windowed-rate / histogram-pNN queries and a "
             "convergence series tee'd from the servers' round history "
             "(fedml_convergence_rounds_to_target); unset = no recorder, "
             "no timer, bit-identical default path."),
    FlagSpec("timeline_dir", "str", None,
             "Directory timeline segment files are flushed into; derived: "
             "<cwd>/perf_timeline."),
    FlagSpec("timeline_interval_s", "float", 1.0,
             "Timeline sampling cadence on the timer wheel."),
    FlagSpec("timeline_capacity", "int", 512,
             "Timeline ring capacity in samples (oldest evicted first — "
             "the bound that keeps recorder memory constant under "
             "sustained sampling); segments flush every capacity/2 "
             "samples."),
    FlagSpec("profile_rounds", "str", None,
             "Profile window for per-program device-time attribution: 'n' "
             "traces rounds 0..n-1, 'k:n' traces n rounds starting at k "
             "(programmatic jax.profiler start/stop around the sim "
             "engine's round chunks; unset = no tracing, bit-identical "
             "default path)."),
    FlagSpec("profile_dir", "str", None,
             "Directory the profiler trace + attribution JSON land in; "
             "derived: <cwd>/profile_traces."),
    # -- multi-host ----------------------------------------------------------
    FlagSpec("coordinator_address", "str", None,
             "jax.distributed coordinator host:port "
             "($JAX_COORDINATOR_ADDRESS fallback)."),
    FlagSpec("num_processes", "int", None,
             "jax.distributed process count ($JAX_NUM_PROCESSES fallback)."),
    FlagSpec("process_id", "int", None,
             "jax.distributed process id ($JAX_PROCESS_ID fallback)."),
    # -- multi-tenant control plane (fedml_tpu/sched/multi_tenant.py) ---------
    FlagSpec("mt_job_id", "str", None,
             "Tenant job id under a multi-tenant control plane: namespaces "
             "the job's run_id, journal roots (<journal_root>/job_<id>/), "
             "and metric label (job=<id>); unset = single-job run, every "
             "path bit-identical to before the flag existed."),
    FlagSpec("mt_weight", "float", 1.0,
             "Fair-share weight of this tenant's job: the gang scheduler "
             "charges each granted round's measured wall time / weight to "
             "the job's virtual clock, so a weight-2 job receives ~2x the "
             "mesh time of a weight-1 sibling."),
    FlagSpec("mt_priority", "int", 0,
             "Strict priority class of this tenant's job: higher classes "
             "win every round-boundary grant over lower ones (preemption "
             "is at round boundaries only — a running round is never "
             "aborted); fair share applies within a class."),
    FlagSpec("mt_slots", "int", 1,
             "Concurrent mesh slots the multi-tenant gang scheduler grants: "
             "how many tenants' (virtual) rounds may run on the shared "
             "mesh/host pool at once."),
    FlagSpec("mt_shared_aot_dir", "str", None,
             "Shared AOT program-store root for all tenants of one control "
             "plane: jobs with the same tracing fingerprint deserialize "
             "each other's exported round/eval programs instead of "
             "recompiling (unset = per-config aot_programs_dir semantics)."),
    FlagSpec("mt_submesh_shape", "str", None,
             "Per-job submesh shape ('clients:2' / 'silo:1,data:2') the "
             "control plane carves out of the fleet's device array: each "
             "admitted job leases a DISJOINT contiguous submesh and its "
             "rounds run genuinely concurrently with its siblings' instead "
             "of time-slicing the full mesh; unset (or shapes that do not "
             "tile the fleet — see mt_submesh_jobs) = PR-14 time-sliced "
             "gate semantics, bit-identical."),
    FlagSpec("mt_submesh_jobs", "int", None,
             "Number of disjoint submeshes to carve (the fleet partition "
             "degree): mt_submesh_shape x mt_submesh_jobs device totals "
             "must fit in the fleet or the plan is rejected and the "
             "scheduler falls back to the time-sliced gate; derived: "
             "fleet size // submesh size."),
    FlagSpec("mt_quota_burst", "float", 0.0,
             "Token-bucket admission quota per tenant, in grants: a job "
             "spends one token per granted round and the bucket refills at "
             "1/mt_quota_refill_s tokens per second up to this burst cap, "
             "so one tenant cannot starve the fleet between round "
             "boundaries no matter its weight; 0 = quota disabled "
             "(fair-share only, bit-identical to before the flag existed)."),
    FlagSpec("mt_quota_refill_s", "float", 1.0,
             "Seconds to refill ONE admission token of the mt_quota_burst "
             "bucket (the steady-state grant period a quota-capped tenant "
             "converges to)."),
    # -- serving -------------------------------------------------------------
    FlagSpec("model_publish_dir", "str", None,
             "Continuous model publication directory: the cross-silo servers "
             "(sync + buffered-async) atomically write a version-stamped "
             "params file + MANIFEST.json at every (virtual-)round version "
             "bump so serving workers can hot-swap the live model (unset = "
             "no publish writes, serving-free runs bit-identical to before "
             "the flag existed)."),
    FlagSpec("model_publish_keep", "int", 5,
             "Published param-file versions retained on disk (older versions "
             "are pruned; the manifest-referenced file is never pruned)."),
    FlagSpec("end_point_name", "str", None,
             "Serving endpoint name; derived: 'ep-<run_id>'."),
    FlagSpec("serving_model_name", "str", None,
             "Model card name for deploy; derived: cfg.model."),
    FlagSpec("model_version", "str", "v1", "Model card version for deploy."),
    FlagSpec("gateway_port", "int", 0,
             "Tenant-routed serving gateway listen port (0 = ephemeral): "
             "one HTTP front door for a shared worker fleet, routing each "
             "request's tenant id to the worker bound to that tenant's "
             "model_publish_dir."),
    FlagSpec("gateway_max_batch", "int", 8,
             "Gateway-side coalescing batch cap per tenant: requests for "
             "the same tenant are batched at the gateway before the "
             "worker's own micro-batcher sees them."),
    FlagSpec("gateway_flush_ms", "float", 2.0,
             "Gateway batching window per tenant in milliseconds — how "
             "long an under-filled tenant batch waits for co-tenants' "
             "rows before flushing to the worker."),
)


def cfg_extra(cfg, name: str, default: Any = _UNSET) -> Any:
    """Read the declared flag ``name`` from ``cfg``.

    Resolution order matches the historical duck-typed behavior: a direct
    attribute on ``cfg`` wins (tests ``setattr`` flags straight onto Config,
    and ``Config.__getattr__`` itself falls through to ``extra``), then the
    ``cfg.extra`` dict, then ``default`` (the registry default when the call
    site passes none).  ``cfg=None`` short-circuits to the default — several
    constructors accept an optional config.

    Raises ``KeyError`` for names missing from :data:`FLAGS`: an undeclared
    flag read is a bug here exactly like it is in GL001.
    """
    spec = FLAGS.get(name)
    if spec is None:
        raise KeyError(
            f"undeclared extra flag {name!r} — declare it in fedml_tpu/core/flags.py")
    fallback = spec.default if default is _UNSET else default
    if cfg is None:
        return fallback
    value = getattr(cfg, name, _UNSET)
    if value is _UNSET:
        extra = getattr(cfg, "extra", None) or {}
        value = extra.get(name, _UNSET)  # graftlint: disable=GL001(the accessor itself)
    return fallback if value is _UNSET else value


def cfg_extra_present(cfg, name: str) -> bool:
    """Registry-checked membership: is the declared flag ``name`` explicitly
    SET on ``cfg``?  The value-resolution twin of :func:`cfg_extra` for the
    ``"name" in cfg.extra`` idiom — it follows the same resolution order (a
    direct attribute counts as set, then the ``extra`` dict), and unlike
    ``cfg_extra`` it keeps present-but-``None`` distinct from absent.

    Raises ``KeyError`` for undeclared names, exactly like :func:`cfg_extra`.
    """
    if name not in FLAGS:
        raise KeyError(
            f"undeclared extra flag {name!r} — declare it in fedml_tpu/core/flags.py")
    if cfg is None:
        return False
    if getattr(cfg, name, _UNSET) is not _UNSET:
        return True
    extra = getattr(cfg, "extra", None) or {}
    return name in extra  # graftlint: disable=GL001(the membership accessor itself)


def set_cfg_extra(cfg, name: str, value: Any) -> Any:
    """Registry-checked WRITE of the declared flag ``name`` into
    ``cfg.extra`` (the one blessed mutation idiom — harness code seeding a
    flag for downstream readers).  Returns ``value`` so assignments can
    chain.  Raises ``KeyError`` for undeclared names."""
    if name not in FLAGS:
        raise KeyError(
            f"undeclared extra flag {name!r} — declare it in fedml_tpu/core/flags.py")
    extra = getattr(cfg, "extra", None)
    if extra is None:
        extra = {}
        cfg.extra = extra
    extra[name] = value
    return value


def render_flag_reference() -> str:
    """The generated flag-reference markdown (checked in as ``docs/FLAGS.md``)."""
    lines = [
        "# `cfg.extra` flag reference",
        "",
        "Generated from `fedml_tpu/core/flags.py` — regenerate with",
        "`python -m fedml_tpu.core.flags > docs/FLAGS.md` after editing the",
        "registry.  Every flag is read through `cfg_extra(cfg, name, default)`;",
        "the GL001 lint rule fails tier-1 on undeclared reads and dead",
        "declarations, so this table is complete by construction.",
        "",
        "| Flag | Type | Default | Description |",
        "|---|---|---|---|",
    ]
    for name in sorted(FLAGS):
        s = FLAGS[name]
        default = "`None`" if s.default is None else f"`{s.default!r}`"
        doc = s.doc.replace("|", "\\|")  # keep literal pipes out of the table grid
        lines.append(f"| `{name}` | {s.type} | {default} | {doc} |")
    lines.append("")
    return "\n".join(lines)


if __name__ == "__main__":
    print(render_flag_reference(), end="")
