"""RNG discipline.

The reference seeds python/numpy/torch globally once (``python/fedml/__init__.py:105-110``)
and re-seeds numpy per round for client sampling
(``simulation/sp/fedavg/fedavg_api.py:132`` — ``np.random.seed(round_idx)``).
Global mutable seeds do not compose with JAX tracing, so here every source of
randomness is an explicit ``jax.random`` key derived by pure folding:

    root key  --fold(round)--> round key --fold(client)--> client key

which makes every client/round stream reproducible and independent of execution
order, device count, or sharding layout — the property that lets the MESH
backend and the sequential SP backend produce identical streams.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def root_key(seed: int) -> jax.Array:
    return jax.random.PRNGKey(seed)


def round_key(key: jax.Array, round_idx) -> jax.Array:
    return jax.random.fold_in(key, round_idx)


def client_key(key: jax.Array, client_idx) -> jax.Array:
    # Disjoint stream per client: fold with an offset tag so that
    # client_key(round_key(k, r), c) never collides with round_key(k, r').
    return jax.random.fold_in(jax.random.fold_in(key, 0x636C69), client_idx)


def sample_clients(
    key: jax.Array, round_idx, client_num_in_total: int, client_num_per_round: int
) -> jax.Array:
    """Sample a per-round subset of client indices, without replacement.

    Matches the semantics (not the bit-stream) of the reference's
    ``_client_sampling`` (``fedavg_api.py:127-141``): if all clients fit, take
    everyone; else a uniform subset seeded by the round index.  Runs inside jit
    (permutation + static slice), so sampling never triggers a retrace
    (SURVEY.md §7 hard part 2).
    """
    if client_num_in_total <= client_num_per_round:
        return jnp.arange(client_num_in_total, dtype=jnp.int32)
    k = round_key(key, round_idx)
    perm = jax.random.permutation(k, client_num_in_total)
    return perm[:client_num_per_round].astype(jnp.int32)


def sample_clients_np(seed_round: int, client_num_in_total: int, client_num_per_round: int) -> np.ndarray:
    """Bit-exact replica of the reference's sampler for parity tests:
    ``np.random.seed(round_idx); np.random.choice(range(n), m, replace=False)``
    (``simulation/sp/fedavg/fedavg_api.py:127-141``)."""
    if client_num_in_total == client_num_per_round:
        return np.arange(client_num_in_total, dtype=np.int64)
    rs = np.random.RandomState(seed_round)
    return np.array(rs.choice(range(client_num_in_total), client_num_per_round, replace=False))


def seed_everything(seed: int) -> None:
    """Seed host-side numpy/python RNGs (data partitioning, shuffling).

    Device-side randomness never touches these — it flows through explicit
    keys above.  Mirrors reference ``__init__.py:105-110`` minus torch.
    """
    import random

    random.seed(seed)
    np.random.seed(seed)
