"""Ahead-of-time program store — kill recurring compilation (ISSUE 7).

The instrumented multichip dryrun attributed the historical rc=124 driver
timeout to ~30 minutes of *recurring* XLA work per run (hierarchical_round
1236 s + ring_gossip 587 s): every process restart re-traced and re-lowered
the same round programs from Python even though nothing about the run had
changed.  Production FL servers restart constantly (deploys, preemptions,
cohort reshapes), so cold-start-to-first-round is a first-class cost — the
communication-perspective survey (2405.20431) and the cross-silo backend
study (2604.10859) both call out server startup/dispatch latency at fleet
scale.

This module is the fix: a persistent **program store** of
``jax.export``-serialized programs, keyed by a stable fingerprint of
everything that affects tracing —

    (site, topology/config, mesh shape + axis names, the argument pytree's
     structure/shapes/dtypes [which subsumes the model variable tree],
     hparams, chunk size / donation gating / fused-kernel + codec flags,
     jax + jaxlib version, backend + device kind + device count)

A warm process **deserializes the lowered StableHLO instead of re-tracing**,
and the one remaining XLA compile of the deserialized module goes through the
ordinary ``jax.jit`` dispatch path — which consults the shared persistent
compilation cache (``core/cache.py``), so across processes the executable
itself is also reused.  Measured on CPU: deserialize ~5 ms + cached compile
~0.05 s vs multi-second (sim) to multi-minute (hierarchical) re-trace +
re-compile.

Design constraints honored here:

- **Never a crash.**  Corrupt, truncated, or version-mismatched entries are
  discarded and rebuilt; an unexportable program (unsupported primitive,
  foreign custom call) falls back to the plain jitted function.  The store
  can only ever cost a rebuild, not a run.
- **Cross-process safe.**  Entries are written to a temp file and
  ``os.replace``d into place (readers see an old or a complete new entry,
  never a torn one); builders serialize on an advisory ``flock`` per entry so
  N restarting processes produce ONE export, and the waiters load it.
- **Default path bit-identical.**  Everything is gated on the registered
  ``extra.aot_programs`` flag; unset means :func:`store_from_config` returns
  ``None`` and every call site runs the exact pre-existing ``jax.jit`` code.
- **Observable.**  ``fedml_aot_{hits,misses,exports}_total`` counters and
  ``fedml_aot_{load,build}_seconds`` histograms land in the global registry,
  and each load/build emits an obs-trail record through the caller's sink.

Entries live under the same host-fingerprinted repo-root cache directory as
the XLA persistent cache (``core/cache.py``): ``.jax_cache-<host>/aot_programs``.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
import logging
import os
import re
import tempfile
import time
from typing import Any, Callable, Iterable, Optional

from ..obs import registry as obsreg
from . import cache as cachelib
from .flags import cfg_extra

log = logging.getLogger("fedml_tpu")

__all__ = [
    "ProgramStore", "StoredProgram", "store_from_config", "default_store_dir",
    "program_key", "tree_signature", "mesh_signature", "config_signature",
    "export_program",
]

#: on-disk entry format: MAGIC + one json meta line + the serialized Exported.
#: Bump the magic when the envelope changes — old entries are then discarded
#: as corrupt and rebuilt, never misread.
_MAGIC = b"FMLAOT1\n"

AOT_HITS = obsreg.REGISTRY.counter(
    "fedml_aot_hits_total",
    "AOT program-store lookups served from a persisted entry (no re-trace).",
)
AOT_MISSES = obsreg.REGISTRY.counter(
    "fedml_aot_misses_total",
    "AOT program-store lookups that had to build (trace + export) the program.",
)
AOT_EXPORTS = obsreg.REGISTRY.counter(
    "fedml_aot_exports_total",
    "Programs export-serialized and written to the store.",
)
AOT_LOAD_TIME = obsreg.REGISTRY.histogram(
    "fedml_aot_load_seconds",
    "Wall time to read + deserialize a stored program.",
)
AOT_BUILD_TIME = obsreg.REGISTRY.histogram(
    "fedml_aot_build_seconds",
    "Wall time to build (trace + lower + export) a program on a store miss.",
)
PROGRAM_FLOPS = obsreg.REGISTRY.gauge(
    "fedml_program_flops",
    "XLA cost-model FLOPs of one compiled program (extra.cost_model_gauges).",
    labels=("program",),
)
PROGRAM_BYTES = obsreg.REGISTRY.gauge(
    "fedml_program_bytes_accessed",
    "XLA cost-model bytes accessed (HBM traffic) of one compiled program "
    "(extra.cost_model_gauges).",
    labels=("program",),
)

#: memory-address hex in default reprs would break cross-process fingerprint
#: stability; scrub it before hashing
_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")


def _canon(v: Any) -> Any:
    """Canonical JSON-able form of a key component — deterministic across
    processes (sorted dicts, lists for tuples, reprs scrubbed of addresses)."""
    if isinstance(v, dict):
        return {str(k): _canon(v[k]) for k in sorted(v, key=str)}
    if isinstance(v, (list, tuple, set, frozenset)):
        items = sorted(v, key=str) if isinstance(v, (set, frozenset)) else v
        return [_canon(x) for x in items]
    if isinstance(v, (str, bool)) or v is None:
        return v
    if isinstance(v, (int, float)):
        return v
    if isinstance(v, bytes):
        return v.hex()
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return _canon(dataclasses.asdict(v))
    return f"{type(v).__module__}.{type(v).__name__}:{_ADDR_RE.sub('0x', repr(v))}"


def tree_signature(tree: Any) -> list:
    """``[(keypath, shape, dtype), ...]`` for every leaf — the structure +
    shapes + dtypes component of a program fingerprint (covers the model
    variable tree, client-state stacks, data stacks, rng keys...)."""
    if tree is None:
        return []
    import jax

    leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in leaves:
        shape = list(getattr(leaf, "shape", ()))
        dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
        out.append([jax.tree_util.keystr(path), shape, dtype])
    return out


def mesh_signature(mesh: Any) -> Optional[dict]:
    """Axis names + sizes (+ device platform) of a ``jax.sharding.Mesh``."""
    if mesh is None:
        return None
    try:
        devs = mesh.devices.ravel()
        return {
            "axes": list(mesh.axis_names),
            "shape": [int(mesh.shape[a]) for a in mesh.axis_names],
            "platform": str(getattr(devs[0], "platform", "")),
        }
    except Exception:
        return {"repr": _ADDR_RE.sub("0x", repr(mesh))}


#: per-run values that do NOT affect tracing (paths, ports, endpoints, ids) —
#: excluded so a redeploy with a new run_id still hits the store.  Everything
#: else in the config rides into the fingerprint: over-inclusion can only
#: cost a rebuild, under-inclusion could serve the wrong program.
_VOLATILE_CFG_KEYS = {
    "run_id", "metrics_jsonl_path", "obs_jsonl_path", "otlp_endpoint",
    "metrics_port", "aot_programs", "aot_programs_dir", "population_store",
    "checkpoint_dir", "server_journal_dir", "client_journal_dir",
    "model_publish_dir", "global_model_file_path", "grpc_base_port",
    "tcp_base_port", "grpc_ip_config", "tcp_ip_config", "mqtt_host",
    "mqtt_port", "object_store_url", "coordinator_address", "process_id",
    "num_processes",
    # multi-tenant identity/scheduling knobs (ISSUE 14): two tenants whose
    # recipes differ only in job id / fair-share policy trace the SAME
    # programs — stripping these is what makes the shared store a cross-job
    # warm start instead of N cold ones
    "mt_job_id", "mt_weight", "mt_priority", "mt_slots", "mt_shared_aot_dir",
    # observability-only knobs (ISSUE 16): recorders, SLO watchdogs, and
    # export encodings never change what gets traced — two runs that differ
    # only in telemetry must share the same stored programs
    "otlp_protocol", "flight_recorder", "flight_dir", "flight_capacity",
    "flight_window_s", "slo_specs", "slo_interval_s", "slo_flight_dump",
    "cost_model_gauges",
}


def config_signature(cfg: Any) -> Optional[dict]:
    """The run config minus volatile per-run values, canonicalized.  Broad on
    purpose: hparams, topology knobs, codec / fused-kernel / trust flags all
    change the traced program and must key it."""
    if cfg is None:
        return None
    d = dict(getattr(cfg, "__dict__", {}))
    extra = dict(d.get("extra") or {})
    for k in _VOLATILE_CFG_KEYS:
        d.pop(k, None)
        extra.pop(k, None)
    d["extra"] = extra
    return _canon(d)


def program_key(site: str, *, mesh: Any = None, trees: Optional[dict] = None,
                hparams: Any = None, config: Any = None,
                extra: Optional[dict] = None) -> str:
    """Stable fingerprint for one traced program at one call site.

    ``trees`` maps names to pytrees whose structure/shapes/dtypes key the
    program (pass the example argument tuple — it subsumes the model variable
    tree).  ``config`` takes the output of :func:`config_signature`.  The jax
    + jaxlib versions, backend, device kind, and device count are always
    included — a store written by one toolchain must never serve another.
    """
    import jax
    import jaxlib

    dev = jax.devices()[0]
    components = {
        "site": site,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
        "device_kind": str(getattr(dev, "device_kind", dev.platform)),
        "n_devices": jax.device_count(),
        "mesh": mesh_signature(mesh),
        "trees": {name: tree_signature(t) for name, t in sorted((trees or {}).items())},
        "hparams": _canon(hparams),
        "config": _canon(config) if not isinstance(config, (dict, type(None))) else config,
        "extra": _canon(extra),
    }
    blob = json.dumps(components, sort_keys=True, separators=(",", ":"))
    digest = hashlib.sha256(blob.encode()).hexdigest()
    return f"{site}.{digest[:32]}"


def export_program(jitted: Callable, example_args: tuple):
    """Trace + lower ``jitted`` at ``example_args`` into a serializable
    ``jax.export.Exported``.  Retries with the TPU custom-call safety check
    waived (Pallas kernels lower to ``tpu_custom_call``, which jax.export
    refuses by default because its ABI is toolchain-pinned — exactly what the
    version-fingerprinted store already guarantees)."""
    from jax import export

    try:
        return export.export(jitted)(*example_args)
    except Exception:
        return export.export(
            jitted,
            disabled_checks=[export.DisabledSafetyCheck.custom_call("tpu_custom_call")],
        )(*example_args)


def record_program_cost(compiled, key: str) -> Optional[dict]:
    """Publish the XLA cost model's flops / bytes-accessed for one compiled
    program as ``fedml_program_flops`` / ``fedml_program_bytes_accessed``
    gauges labeled ``program=key`` (ISSUE 16 satellite: the SLO engine can
    then watch MFU-style ratios, and a perf regression shows up as a cost
    delta next to the wall-clock delta instead of a mystery).

    Returns ``{"flops", "bytes_accessed"}`` or ``None`` when the runtime
    exposes no cost analysis (interpreters, some CPU paths) — callers treat
    the gauges as best-effort."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):  # older jax: one dict per device
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0))
        bytes_accessed = float(ca.get("bytes accessed",
                                      ca.get("bytes_accessed", 0.0)))
    except Exception:
        return None
    PROGRAM_FLOPS.set(flops, program=key)
    PROGRAM_BYTES.set(bytes_accessed, program=key)
    return {"flops": flops, "bytes_accessed": bytes_accessed}


def default_store_dir() -> str:
    """``<repo>/.jax_cache-<host>/aot_programs`` — the same host-fingerprinted
    repo-root cache dir as the XLA persistent compilation cache, so the two
    halves of a warm start (skip the re-trace, skip the re-compile) travel
    together."""
    return os.path.join(cachelib.cache_dir(), "aot_programs")


class StoredProgram:
    """One resolved store entry: the deserialized/just-built ``Exported`` plus
    where it came from.  ``call`` is the traceable entry point — wrap it in
    ``jax.jit`` (optionally with ``donate_argnums``) exactly like the original
    function; the wrapper's compile rides the persistent compilation cache."""

    __slots__ = ("exported", "key", "from_cache", "path")

    def __init__(self, exported, key: str, from_cache: bool, path: str):
        self.exported = exported
        self.key = key
        self.from_cache = from_cache
        self.path = path

    @property
    def call(self) -> Callable:
        return self.exported.call

    def bind(self, example_args: Optional[tuple] = None,
             donate_argnums: tuple = ()) -> Callable:
        """A jitted callable for this program; with ``example_args`` it is
        AOT-compiled now (compile time attributable to load, not round 1)."""
        import jax

        wrapper = jax.jit(self.exported.call, donate_argnums=tuple(donate_argnums))
        if example_args is not None:
            try:
                return wrapper.lower(*example_args).compile()
            except Exception:
                pass
        return wrapper


class ProgramStore:
    """Persistent, cross-process store of exported programs.

    ``get_or_build(key, build_fn)`` is the whole contract: return the stored
    program for ``key`` if a valid entry exists, else call ``build_fn()``
    (which must return a ``jax.export.Exported``), persist it atomically, and
    return it.  Returns ``None`` only when ``build_fn`` itself fails — the
    caller then falls back to its plain jitted path.
    """

    def __init__(self, root: str, trail: Optional[Callable[[dict], None]] = None,
                 cost_gauges: bool = False):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.trail = trail  # obs-trail sink: one record per load/build
        # extra.cost_model_gauges: publish XLA cost-model flops/bytes per
        # program at bind time (forces the AOT compile at load, so the cost
        # is attributable there — same trade as cached_jit's eager flag)
        self.cost_gauges = bool(cost_gauges)

    # -- paths ---------------------------------------------------------------
    def _path(self, key: str) -> str:
        safe = re.sub(r"[^A-Za-z0-9._-]", "_", key)
        return os.path.join(self.root, safe + ".jaxprog")

    def entries(self) -> list[str]:
        try:
            return sorted(f for f in os.listdir(self.root) if f.endswith(".jaxprog"))
        except OSError:
            return []

    # -- the contract --------------------------------------------------------
    def get_or_build(self, key: str, build_fn: Callable[[], Any]) -> Optional[StoredProgram]:
        prog = self._load(key)
        if prog is not None:
            return prog
        with self._entry_lock(key):
            # double-check under the lock: a concurrent process may have
            # finished the build while this one waited on the flock
            prog = self._load(key)
            if prog is not None:
                return prog
            AOT_MISSES.inc()
            t0 = time.perf_counter()
            try:
                exported = build_fn()
            except Exception as e:  # never a crash: fall back to plain jit
                log.warning("aot: build for %s failed (%s: %s) — falling back "
                            "to the un-stored jit path", key, type(e).__name__, e)
                return None
            build_s = time.perf_counter() - t0
            AOT_BUILD_TIME.observe(build_s)
            path = self._write(key, exported)
            self._record("build", key, build_s, hit=False)
            return StoredProgram(exported, key, from_cache=False, path=path)

    def warm(self, items: Iterable[tuple[str, Callable[[], Any]]]) -> dict:
        """Pre-resolve every (key, build_fn) a run will need before round 0.
        Returns ``{"loaded": n, "built": n, "failed": n}`` — a server calls
        this at startup so round 0 never pays a trace."""
        out = {"loaded": 0, "built": 0, "failed": 0}
        for key, build_fn in items:
            prog = self.get_or_build(key, build_fn)
            if prog is None:
                out["failed"] += 1
            elif prog.from_cache:
                out["loaded"] += 1
            else:
                out["built"] += 1
        return out

    def cached_jit(self, fn: Callable, example_args: tuple, *, key: str,
                   donate_argnums: tuple = (), eager: bool = False) -> Callable:
        """jit-through-the-store: the drop-in replacement for
        ``jax.jit(fn)`` at a traced-per-run call site.  Store hit → the
        deserialized program (re-trace skipped); miss → trace once, export,
        persist; any failure → plain ``jax.jit(fn)``.  Donation is applied to
        the wrapper, never baked into the stored artifact (the artifact stays
        valid for both the donating and non-donating caller)."""
        import jax

        prog = self.get_or_build(
            key, lambda: export_program(jax.jit(fn), example_args))
        if prog is None:
            return jax.jit(fn, donate_argnums=tuple(donate_argnums))
        bound = prog.bind(
            example_args if (eager or self.cost_gauges) else None,
            donate_argnums)
        if self.cost_gauges:
            record_program_cost(bound, key)
        return bound

    # -- on-disk format ------------------------------------------------------
    def _load(self, key: str) -> Optional[StoredProgram]:
        path = self._path(key)
        t0 = time.perf_counter()
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            return None
        try:
            exported = self._decode(blob)
        except Exception as e:
            # corrupt / truncated / version-mismatched: discard, rebuild
            log.warning("aot: discarding unusable entry %s (%s: %s)",
                        path, type(e).__name__, e)
            with contextlib.suppress(OSError):
                os.remove(path)
            return None
        load_s = time.perf_counter() - t0
        AOT_HITS.inc()
        AOT_LOAD_TIME.observe(load_s)
        self._record("load", key, load_s, hit=True)
        return StoredProgram(exported, key, from_cache=True, path=path)

    @staticmethod
    def _decode(blob: bytes):
        if not blob.startswith(_MAGIC):
            raise ValueError("bad magic")
        rest = blob[len(_MAGIC):]
        nl = rest.find(b"\n")
        if nl < 0:
            raise ValueError("truncated header")
        meta = json.loads(rest[:nl].decode())
        payload = rest[nl + 1:]
        if int(meta.get("payload_len", -1)) != len(payload):
            raise ValueError("truncated payload")
        import jax
        import jaxlib

        if meta.get("jax") != jax.__version__ or meta.get("jaxlib") != jaxlib.__version__:
            raise ValueError(
                f"toolchain mismatch (entry {meta.get('jax')}/{meta.get('jaxlib')}, "
                f"running {jax.__version__}/{jaxlib.__version__})")
        from jax import export

        return export.deserialize(bytearray(payload))

    def _write(self, key: str, exported) -> str:
        import jax
        import jaxlib

        payload = bytes(exported.serialize())
        meta = {
            "key": key,
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "payload_len": len(payload),
            "created_unix": round(time.time(), 3),
        }
        blob = _MAGIC + json.dumps(meta, sort_keys=True).encode() + b"\n" + payload
        path = self._path(key)
        fd, tmp = tempfile.mkstemp(dir=self.root, prefix=".tmp_", suffix=".jaxprog")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)  # atomic: readers see old or complete new
        except OSError as e:
            log.warning("aot: could not persist %s (%s) — program stays "
                        "process-local", path, e)
            with contextlib.suppress(OSError):
                os.remove(tmp)
            return path
        AOT_EXPORTS.inc()
        return path

    # -- cross-process coordination ------------------------------------------
    @contextlib.contextmanager
    def _entry_lock(self, key: str):
        """Advisory per-entry flock: N restarting processes building the same
        program serialize into ONE export; the waiters load the winner's
        entry.  Reads never lock (atomic replace keeps them safe)."""
        lock_path = self._path(key) + ".lock"
        try:
            import fcntl
        except ImportError:  # non-posix: best effort, builds may duplicate
            yield
            return
        fd = os.open(lock_path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            with contextlib.suppress(OSError):
                fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    # -- observability -------------------------------------------------------
    def _record(self, event: str, key: str, seconds: float, hit: bool) -> None:
        if self.trail is None:
            return
        try:
            self.trail({
                "kind": "metric", "metric": "aot_program_load", "event": event,
                "program": key, "value": round(seconds, 6), "hit": bool(hit),
            })
        except Exception:  # the trail is best-effort telemetry, never fatal
            pass


def store_from_config(cfg, trail: Optional[Callable[[dict], None]] = None
                      ) -> Optional[ProgramStore]:
    """The one gate: ``extra.aot_programs`` unset/falsy → ``None`` (every call
    site then runs its pre-existing ``jax.jit`` path, bit-identical).  Set →
    a store rooted at ``extra.aot_programs_dir`` (default: the repo-root
    cache dir's ``aot_programs/``)."""
    if cfg is None or not cfg_extra(cfg, "aot_programs"):
        return None
    root = cfg_extra(cfg, "aot_programs_dir") or default_store_dir()
    try:
        return ProgramStore(str(root), trail=trail,
                            cost_gauges=bool(cfg_extra(cfg, "cost_model_gauges")))
    except OSError as e:
        log.warning("aot: store root %s unusable (%s) — running without the "
                    "program store", root, e)
        return None
