"""Platform / optimizer / backend name constants.

Mirrors the role of the reference's ``python/fedml/constants.py`` (platform and
federated-optimizer string constants) so YAML recipes written against the
reference's ``fedml_config.yaml`` vocabulary keep working unchanged.
"""

# ---------------------------------------------------------------------------
# Training platforms (reference: constants.py FEDML_TRAINING_PLATFORM_*)
# ---------------------------------------------------------------------------
TRAINING_PLATFORM_SIMULATION = "simulation"
TRAINING_PLATFORM_CROSS_SILO = "cross_silo"
TRAINING_PLATFORM_CROSS_DEVICE = "cross_device"
TRAINING_PLATFORM_CROSS_CLOUD = "cross_cloud"
TRAINING_PLATFORM_SERVING = "model_serving"
TRAINING_PLATFORM_CENTRALIZED = "centralized"

# Simulation backends.  The reference dispatches on ``args.backend`` in
# ``simulation/simulator.py``; on TPU the native backend is the sharded
# single-controller program ("MESH").  "SP" is kept as the sequential
# single-device reference path (useful for numerics regression tests), and
# "MULTIPROCESS" maps to jax.distributed multi-host execution.
SIMULATION_BACKEND_SP = "sp"
SIMULATION_BACKEND_MESH = "MESH"  # TPU-native: clients sharded over mesh axis
SIMULATION_BACKEND_MPI = "MPI"  # accepted alias -> multiprocess jax.distributed
SIMULATION_BACKEND_NCCL = "NCCL"  # accepted alias -> MESH (collective-native)

# ---------------------------------------------------------------------------
# Federated optimizers (reference: FedML_FEDERATED_OPTIMIZER_*)
# ---------------------------------------------------------------------------
FEDERATED_OPTIMIZER_FEDAVG = "FedAvg"
FEDERATED_OPTIMIZER_FEDAVG_SEQ = "FedAvg_seq"
FEDERATED_OPTIMIZER_FEDOPT = "FedOpt"
FEDERATED_OPTIMIZER_FEDOPT_SEQ = "FedOpt_seq"
FEDERATED_OPTIMIZER_FEDPROX = "FedProx"
FEDERATED_OPTIMIZER_FEDNOVA = "FedNova"
FEDERATED_OPTIMIZER_FEDDYN = "FedDyn"
FEDERATED_OPTIMIZER_SCAFFOLD = "SCAFFOLD"
FEDERATED_OPTIMIZER_MIME = "Mime"
FEDERATED_OPTIMIZER_FEDSGD = "FedSGD"
FEDERATED_OPTIMIZER_ASYNC_FEDAVG = "Async_FedAvg"
FEDERATED_OPTIMIZER_FEDGAN = "FedGan"
FEDERATED_OPTIMIZER_HIERARCHICAL_FL = "HierarchicalFL"
FEDERATED_OPTIMIZER_TURBO_AGGREGATE = "TA"
FEDERATED_OPTIMIZER_DECENTRALIZED_FL = "decentralized_fl"
FEDERATED_OPTIMIZER_VERTICAL_FL = "vertical_fl"
FEDERATED_OPTIMIZER_SPLIT_NN = "split_nn"
FEDERATED_OPTIMIZER_FEDGKT = "FedGKT"
FEDERATED_OPTIMIZER_FEDNAS = "FedNAS"
FEDERATED_OPTIMIZER_FEDSEG = "FedSeg"
# federated LoRA finetuning (reference spotlight_prj/fedllm run_fedllm.py)
FEDERATED_OPTIMIZER_FEDLLM = "FedLLM"
# Fork research: CKA layer-selective personalized aggregation
# (my_research/.../MyAvgAPI_7.py; simulator.py:88-95 dispatches "MyAgg-*")
FEDERATED_OPTIMIZER_MYAVG = "MyAvg"
# only the -7 variant is implemented; MyAgg-4/5/6 differ materially in the
# reference (no CKA / no projection correction) and must not silently alias
FEDERATED_OPTIMIZER_MYAVG_ALIASES = ("MyAvg", "MyAgg-7")

# Communication backends (reference: fedml_comm_manager.py:133-207)
COMM_BACKEND_INPROC = "INPROC"  # loopback fake for tests (new; SURVEY.md §4)
COMM_BACKEND_GRPC = "GRPC"
COMM_BACKEND_MQTT_S3 = "MQTT_S3"
COMM_BACKEND_TCP = "TCP"  # polyglot frame transport (native/ C++ client)
COMM_BACKEND_TRPC = "TRPC"
COMM_BACKEND_MPI = "MPI"
COMM_BACKEND_WEB3 = "WEB3"  # messages as ledger transactions (comm/blockchain.py)
COMM_BACKEND_THETA = "THETASTORE"

# Device / engine
ENGINE_JAX = "jax"

# Dataset names understood by fedml_tpu.data.load (reference data_loader.py:262-530)
DATASETS_IMAGE = ("mnist", "femnist", "cifar10", "cifar100", "cinic10", "fashionmnist",
                  "gld23k", "gld160k")
DATASETS_TEXT = ("shakespeare", "fed_shakespeare", "stackoverflow_nwp", "reddit")
DATASETS_VECTOR = ("stackoverflow_lr", "lending_club")
DATASET_SYNTHETIC = "synthetic"
