"""Client contribution assessment (Shapley values).

Parity with ``core/contribution/``: ``ContributionAssessorManager``
(``contribution_assessor_manager.py:9``), ``gtg_shapley_value.py`` (GTG —
"Guided Truncation Gradient" Shapley: within-round truncated Monte-Carlo over
permutations of client updates), ``leave_one_out.py``.

An "eval" here is a pure function ``eval_fn(agg_vars) -> float`` (accuracy on
held-out data); candidate models are weighted means of client-update subsets —
built with the same ``tree_weighted_mean`` as real aggregation, so assessing
k subsets is k fused reductions, vmap-able if needed.
"""

from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

from ..core import pytree as pt


def _subset_model(stacked_contribs, weights: np.ndarray, mask: np.ndarray, empty_model=None):
    """Aggregate of the masked coalition; the EMPTY coalition is the pre-round
    global model (``empty_model``), not a degenerate normalized mean — the
    weighted mean normalizes weights, so near-zero masks would silently
    reproduce the full-coalition model."""
    import jax.numpy as jnp

    if mask.sum() == 0:
        if empty_model is None:
            raise ValueError("empty coalition requires empty_model")
        return empty_model
    w = jnp.asarray(weights * mask)
    return pt.tree_weighted_mean(stacked_contribs, w)


def leave_one_out(stacked_contribs, weights: np.ndarray, eval_fn: Callable, empty_model=None) -> np.ndarray:
    """v(all) - v(all \\ {i}) per client (leave_one_out.py)."""
    m = len(weights)
    full = float(eval_fn(_subset_model(stacked_contribs, weights, np.ones(m))))
    scores = np.zeros(m)
    for i in range(m):
        mask = np.ones(m)
        mask[i] = 0.0
        scores[i] = full - float(eval_fn(_subset_model(stacked_contribs, weights, mask, empty_model)))
    return scores


def gtg_shapley(
    stacked_contribs,
    weights: np.ndarray,
    eval_fn: Callable,
    empty_model,
    rounds_cap: int = 20,
    eps: float = 1e-3,
    seed: int = 0,
) -> np.ndarray:
    """Truncated Monte-Carlo Shapley (gtg_shapley_value.py): sample client
    permutations, walk marginal contributions, truncate a walk when the
    running value is within eps of the full-coalition value; stop when the
    estimate stabilizes or rounds_cap permutations are used.

    ``empty_model``: the pre-round global variables — v(empty coalition)."""
    rng = np.random.RandomState(seed)
    m = len(weights)
    v_full = float(eval_fn(_subset_model(stacked_contribs, weights, np.ones(m))))
    v_empty = float(eval_fn(empty_model))
    shap = np.zeros(m)
    count = np.zeros(m)
    prev_est = None
    for it in range(rounds_cap):
        perm = rng.permutation(m)
        mask = np.zeros(m)
        v_prev = v_empty
        for pos, i in enumerate(perm):
            if abs(v_full - v_prev) < eps:  # truncation: rest contribute ~0
                marginal = 0.0
                v_curr = v_prev
            else:
                mask[i] = 1.0
                v_curr = float(eval_fn(_subset_model(stacked_contribs, weights, mask, empty_model)))
                marginal = v_curr - v_prev
            shap[i] += marginal
            count[i] += 1
            v_prev = v_curr
        est = shap / np.maximum(count, 1)
        if prev_est is not None and np.max(np.abs(est - prev_est)) < eps / 10:
            break
        prev_est = est
    return shap / np.maximum(count, 1)


class ContributionAssessorManager:
    """Facade with the reference's shape: built from config, runs the chosen
    method after aggregation."""

    def __init__(self, cfg):
        self.enabled = bool(getattr(cfg, "enable_contribution", False))
        self.method = getattr(cfg, "contribution_method", "gtg_shapley")

    def assess(self, stacked_contribs, weights, eval_fn, empty_model=None) -> np.ndarray:
        w = np.asarray(weights, dtype=np.float64)
        if self.method in ("gtg_shapley", "GTG"):
            return gtg_shapley(stacked_contribs, w, eval_fn, empty_model)
        if self.method in ("leave_one_out", "LOO"):
            return leave_one_out(stacked_contribs, w, eval_fn, empty_model)
        raise ValueError(f"unknown contribution_method {self.method!r}")
