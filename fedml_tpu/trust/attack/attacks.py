"""Attack simulations (robustness evaluation).

Parity with ``FedMLAttacker`` (``core/security/fedml_attacker.py:14``) and the
attack classes under ``core/security/attack/``: Byzantine (random/zero/flip),
label flipping (dataset poisoning), model-replacement backdoor, lazy worker.
Privacy attacks (DLG et al.) live in ``dlg.py``.

Byzantine-style attacks are pure transforms of the stacked (m, d) client
update matrix + a per-client malicious mask — they slot into the engine's
``client_hook`` (the point where the reference's
``attack_model_list``/``poison_model`` runs, server-side before aggregation).
Label flipping poisons the host-side dataset before stacking, matching the
reference's ``ClientTrainer.update_dataset`` poisoning hook
(``client_trainer.py:38``).
"""

from __future__ import annotations

import logging
import os
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...core.flags import cfg_extra

log = logging.getLogger("fedml_tpu.trust.attack")


def malicious_mask(m: int, sampled_idx: jax.Array, attacker_ids: Sequence[int]) -> jax.Array:
    """(m,) 1.0 where the sampled client id is an attacker."""
    ids = jnp.asarray(list(attacker_ids), dtype=jnp.int32)
    if ids.size == 0:
        return jnp.zeros((m,), jnp.float32)
    return jnp.any(sampled_idx[:, None] == ids[None, :], axis=1).astype(jnp.float32)


# ---------------------------------------------------------------------------
# Byzantine family (byzantine_attack.py modes: random / zero / flip)
# ---------------------------------------------------------------------------

def byzantine_random(updates: jax.Array, mask: jax.Array, key: jax.Array, scale: float = 1.0) -> jax.Array:
    noise = jax.random.normal(key, updates.shape) * scale
    return jnp.where(mask[:, None] > 0, noise, updates)


def byzantine_zero(updates: jax.Array, mask: jax.Array) -> jax.Array:
    return jnp.where(mask[:, None] > 0, 0.0, updates)


def byzantine_flip(updates: jax.Array, mask: jax.Array, global_flat: jax.Array) -> jax.Array:
    """Sign-flip the delta around the global model (gradient ascent)."""
    flipped = 2.0 * global_flat[None, :] - updates
    return jnp.where(mask[:, None] > 0, flipped, updates)


def model_replacement(updates: jax.Array, mask: jax.Array, global_flat: jax.Array, boost: float) -> jax.Array:
    """Model-replacement backdoor (model_replacement_backdoor_attack.py):
    attacker scales its delta by ``boost`` (typically n/eta) so the averaged
    global becomes its target model."""
    boosted = global_flat[None, :] + boost * (updates - global_flat[None, :])
    return jnp.where(mask[:, None] > 0, boosted, updates)


def lazy_worker(updates: jax.Array, mask: jax.Array, global_flat: jax.Array) -> jax.Array:
    """Lazy/free-rider (lazy_worker.py): returns the global weights untrained."""
    return jnp.where(mask[:, None] > 0, global_flat[None, :], updates)


# ---------------------------------------------------------------------------
# Label flipping (label_flipping_attack.py) — host-side dataset poisoning
# ---------------------------------------------------------------------------

def flip_labels(
    labels: np.ndarray,
    client_idx: list,
    poisoned_clients: Sequence[int],
    original_class: int,
    target_class: int,
) -> np.ndarray:
    """Return a copy of ``labels`` where poisoned clients' samples of
    ``original_class`` become ``target_class``."""
    out = labels.copy()
    for c in poisoned_clients:
        ix = client_idx[c]
        sel = ix[out[ix] == original_class]
        out[sel] = target_class
    return out


def backdoor_pixel_pattern(x: np.ndarray, client_idx: list, poisoned_clients: Sequence[int],
                           target_class: int, labels: np.ndarray, frac: float = 0.5,
                           seed: int = 0):
    """Pixel-pattern backdoor (backdoor_attack.py): stamp a corner trigger on a
    fraction of poisoned clients' images and relabel to the target class.
    Returns (x', labels')."""
    x = x.copy()
    labels = labels.copy()
    rng = np.random.RandomState(seed)
    for c in poisoned_clients:
        ix = client_idx[c]
        n_poison = int(len(ix) * frac)
        sel = rng.choice(ix, size=n_poison, replace=False)
        x[sel, :3, :3, :] = x.max()  # 3x3 corner trigger
        labels[sel] = target_class
    return x, labels


def edge_case_backdoor(x: np.ndarray, client_idx: list, poisoned_clients: Sequence[int],
                       target_class: int, labels: np.ndarray, frac: float = 0.2,
                       seed: int = 0, edge_examples: np.ndarray = None):
    """Edge-case backdoor (reference ``backdoor_attack.py`` edge-case mode,
    Wang et al. NeurIPS'20): poison with inputs from the TAIL of the data
    distribution — rare-looking samples a pixel trigger doesn't need — all
    relabeled to the target.

    ``edge_examples``: the CANONICAL curated edge sets (Southwest airplanes
    / ARDIS digits, ``data/edge_case_examples/data_loader.py:460``) when the
    downloaded files are on disk — poisoned slots are replaced by these
    natural edge images.  Without them the dataset-agnostic stand-in
    synthesizes tail samples by pushing real samples far along their
    deviation from the dataset mean.  Returns (x', labels')."""
    x = x.copy()
    labels = labels.copy()
    rng = np.random.RandomState(seed)
    mean = x.mean(axis=0, keepdims=True)
    scale = 3.0  # how far into the tail the samples are pushed
    if edge_examples is not None and edge_examples.shape[1:] != x.shape[1:]:
        log.warning(
            "edge-case set shape %s != dataset shape %s; falling back to "
            "synthesized tail samples", edge_examples.shape[1:], x.shape[1:],
        )
        edge_examples = None
    if edge_examples is not None:
        # match the DESTINATION distribution's scale: the dataset may be
        # normalized ((x/255-mean)/std for real CIFAR) while the curated
        # sets are raw [0,1] — the reference applies the dataset transform
        # to its edge sets; the dataset-agnostic equivalent is moment
        # matching per channel
        ax = tuple(range(x.ndim - 1))
        e = edge_examples.astype(np.float32)
        e_m, e_s = e.mean(axis=ax), e.std(axis=ax) + 1e-8
        x_m, x_s = x.mean(axis=ax), x.std(axis=ax) + 1e-8
        edge_examples = (e - e_m) / e_s * x_s + x_m
    for c in poisoned_clients:
        ix = client_idx[c]
        n_poison = int(len(ix) * frac)
        if n_poison == 0:
            continue
        sel = rng.choice(ix, size=n_poison, replace=False)
        if edge_examples is not None:
            pick = rng.randint(0, len(edge_examples), size=n_poison)
            x[sel] = edge_examples[pick]
        else:
            x[sel] = mean + scale * (x[sel] - mean)  # amplified deviation = tail
        labels[sel] = target_class
    return x, labels


MODEL_ATTACKS = (
    "byzantine_random", "byzantine_zero", "byzantine_flip",
    "model_replacement", "lazy_worker",
)
DATA_ATTACKS = ("label_flipping", "backdoor", "edge_case_backdoor")
KNOWN_ATTACKS = MODEL_ATTACKS + DATA_ATTACKS


class FedMLAttacker:
    """Singleton-style facade matching the reference API shape
    (``fedml_attacker.py``): enabled by config, exposes
    ``poison_model`` (stacked update matrix) and ``poison_data``
    (host-side dataset, the reference's ``update_dataset`` hook)."""

    def __init__(self, cfg):
        self.cfg = cfg
        self.enabled = bool(getattr(cfg, "enable_attack", False))
        self.attack_type = getattr(cfg, "attack_type", "")
        if self.enabled and self.attack_type not in KNOWN_ATTACKS:
            raise ValueError(
                f"unknown attack_type {self.attack_type!r}; known: {sorted(KNOWN_ATTACKS)}"
            )
        self.attackers = tuple(getattr(cfg, "poisoned_client_list", ()) or ())
        self.boost = float(cfg_extra(cfg, "attack_boost"))
        self.original_class = int(cfg_extra(cfg, "attack_original_class"))
        self.target_class = int(cfg_extra(cfg, "attack_target_class"))
        self.poison_frac = float(cfg_extra(cfg, "attack_poison_frac"))

    def is_model_attack(self) -> bool:
        return self.enabled and self.attack_type in MODEL_ATTACKS

    def is_data_attack(self) -> bool:
        return self.enabled and self.attack_type in DATA_ATTACKS

    def poison_data(self, ds):
        """Poison the host-side FederatedDataset in place-of (returns a new
        dataset) before client shards are stacked — mirrors the poisoning hook
        in ``ClientTrainer.update_dataset`` (``client_trainer.py:38``)."""
        import dataclasses

        if self.attack_type == "label_flipping":
            new_y = flip_labels(
                ds.train_y, ds.client_idx, self.attackers,
                self.original_class, self.target_class,
            )
            return dataclasses.replace(ds, train_y=new_y)
        if self.attack_type == "backdoor":
            new_x, new_y = backdoor_pixel_pattern(
                ds.train_x, ds.client_idx, self.attackers,
                self.target_class, ds.train_y, frac=self.poison_frac,
            )
            return dataclasses.replace(ds, train_x=new_x, train_y=new_y)
        if self.attack_type == "edge_case_backdoor":
            # use the canonical downloaded edge sets when present on disk
            from pathlib import Path

            from ...data.extra_loaders import load_edge_case_sets

            sets = load_edge_case_sets(
                Path(os.path.expanduser(getattr(self.cfg, "data_cache_dir", "") or ".")),
                str(cfg_extra(self.cfg, "edge_case_type")),
            )
            new_x, new_y = edge_case_backdoor(
                ds.train_x, ds.client_idx, self.attackers,
                self.target_class, ds.train_y, frac=self.poison_frac,
                edge_examples=None if sets is None else sets[0],
            )
            return dataclasses.replace(ds, train_x=new_x, train_y=new_y)
        return ds

    def poison_model(self, updates: jax.Array, sampled_idx: jax.Array,
                     global_flat: jax.Array, key: jax.Array) -> jax.Array:
        mask = malicious_mask(updates.shape[0], sampled_idx, self.attackers)
        t = self.attack_type
        if t == "byzantine_random":
            return byzantine_random(updates, mask, key)
        if t == "byzantine_zero":
            return byzantine_zero(updates, mask)
        if t == "byzantine_flip":
            return byzantine_flip(updates, mask, global_flat)
        if t == "model_replacement":
            return model_replacement(updates, mask, global_flat, self.boost)
        if t == "lazy_worker":
            return lazy_worker(updates, mask, global_flat)
        raise ValueError(f"unknown model attack {t!r}")
