"""Privacy attacks: DLG gradient inversion + label revelation.

Parity with ``core/security/attack/dlg_attack.py``,
``invert_gradient_attack.py`` and
``revealing_labels_from_gradients_attack.py``.  DLG ("Deep Leakage from
Gradients", Zhu et al.) reconstructs training inputs by optimizing dummy data
so its gradients match the victim's.  The reference runs an L-BFGS torch loop;
here the matching objective is differentiated with ``jax.grad`` and optimized
with Adam under ``lax.scan`` — one compiled program, TPU-resident.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax


def revealing_labels_from_gradients(last_layer_grad_b: jax.Array) -> jax.Array:
    """Infer which labels were in the victim batch from the last dense layer's
    BIAS gradient (iDLG observation): for CE loss,
    dL/db_c = mean_i (softmax_c - 1{y_i = c}), which is negative exactly when
    class c appears in the batch (for reasonably calibrated logits).

    For weight gradients with non-negative activations (post-ReLU), the same
    sign rule applies to column sums — pass ``grad_w.sum(axis=0)``.

    Returns (classes,) boolean — class judged present.
    """
    return last_layer_grad_b < 0


def dlg_attack(
    grad_fn: Callable,
    victim_grads,
    x_shape: tuple,
    n_classes: int,
    key: jax.Array,
    steps: int = 200,
    lr: float = 0.1,
):
    """Reconstruct (x, y-probs) whose gradients match ``victim_grads``.

    grad_fn(params_free_x, y_soft) -> grads pytree matching victim_grads
    (closed over model params).  Returns (x_hat, y_soft_hat, final_loss).
    """
    kx, ky = jax.random.split(key)
    x0 = jax.random.normal(kx, x_shape) * 0.1
    y0 = jax.random.normal(ky, (x_shape[0], n_classes)) * 0.1
    opt = optax.adam(lr)

    def match_loss(xy):
        x, y_logits = xy
        y_soft = jax.nn.softmax(y_logits, axis=-1)
        g = grad_fn(x, y_soft)
        diffs = jax.tree_util.tree_map(lambda a, b: jnp.sum((a - b) ** 2), g, victim_grads)
        return jax.tree_util.tree_reduce(jnp.add, diffs, jnp.float32(0.0))

    vg = jax.value_and_grad(match_loss)

    def step(carry, _):
        xy, opt_state = carry
        loss, g = vg(xy)
        updates, opt_state = opt.update(g, opt_state, xy)
        xy = optax.apply_updates(xy, updates)
        return (xy, opt_state), loss

    xy0 = (x0, y0)
    (xy, _), losses = jax.lax.scan(step, (xy0, opt.init(xy0)), None, length=steps)
    x_hat, y_logits = xy
    return x_hat, jax.nn.softmax(y_logits, axis=-1), losses[-1]


def _total_variation(x: jax.Array) -> jax.Array:
    """TV prior over trailing spatial dims when present (images); zero for
    flat feature vectors."""
    if x.ndim >= 3:  # (b, h, w, ...) images
        dh = jnp.abs(x[:, 1:, :] - x[:, :-1, :]).mean()
        dw = jnp.abs(x[:, :, 1:] - x[:, :, :-1]).mean()
        return dh + dw
    return jnp.float32(0.0)


def invert_gradient_attack(
    grad_fn: Callable,
    victim_grads,
    x_shape: tuple,
    labels: jax.Array,
    key: jax.Array,
    steps: int = 300,
    lr: float = 0.1,
    tv_weight: float = 1e-2,
    n_classes: int = 0,
):
    """"Inverting Gradients" (Geiping et al. 2020) — the reference's
    ``invert_gradient_attack.py`` variant of DLG: labels are assumed KNOWN
    (recoverable via :func:`revealing_labels_from_gradients`), the matching
    objective is COSINE distance per gradient tensor (magnitude-invariant, so
    it survives gradient clipping/scaling), and a total-variation prior
    regularizes image reconstructions.

    grad_fn(x, y_onehot) -> grads pytree.  Pass ``n_classes`` explicitly for
    models whose last 1-D gradient leaf is NOT the head bias (LayerNorm-final
    or bias-free heads break the heuristic).  Returns (x_hat, final_loss).
    """
    y_onehot = jax.nn.one_hot(
        labels, n_classes or victim_grads_classes(victim_grads, labels)
    )
    x0 = jax.random.normal(key, x_shape) * 0.1
    opt = optax.adam(lr)

    def cosine_loss(x):
        g = grad_fn(x, y_onehot)

        def cos_dist(a, b):
            num = jnp.sum(a * b)
            den = jnp.linalg.norm(a.ravel()) * jnp.linalg.norm(b.ravel()) + 1e-12
            return 1.0 - num / den

        dists = jax.tree_util.tree_map(cos_dist, g, victim_grads)
        match = jax.tree_util.tree_reduce(jnp.add, dists, jnp.float32(0.0))
        return match + tv_weight * _total_variation(x)

    vg = jax.value_and_grad(cosine_loss)

    def step(carry, _):
        x, opt_state = carry
        loss, g = vg(x)
        # signed gradient descent (the paper's choice; more robust to the
        # cosine objective's scale)
        updates, opt_state = opt.update(jax.tree_util.tree_map(jnp.sign, g), opt_state, x)
        x = optax.apply_updates(x, updates)
        return (x, opt_state), loss

    (x_hat, _), losses = jax.lax.scan(step, (x0, opt.init(x0)), None, length=steps)
    return x_hat, losses[-1]


def victim_grads_classes(victim_grads, labels) -> int:
    """Class count from the last bias gradient when present, else labels."""
    leaves = jax.tree_util.tree_leaves(victim_grads)
    for leaf in reversed(leaves):
        if leaf.ndim == 1:
            return int(leaf.shape[0])
    return int(jnp.max(labels)) + 1
