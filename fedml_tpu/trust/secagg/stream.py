"""Streaming pairwise-mask secure aggregation — the field-domain fast path.

Pairwise-mask SecAgg is a SUM over a modular ring, i.e. associative: masked
uploads can fold one at a time into a running field total with peak buffered
<= 2 at any cohort size, and the masks come out ONCE at finalize (survivors'
self-masks subtracted, dropped clients' orphaned pair masks cancelled from
their Shamir-reconstructed seeds) — never by re-buffering the cohort.  This
module holds the codec- and server-side primitives shared by the Shamir
cross-silo protocol (``cross_silo/secagg_shamir.py``) and the simulated-
cohort soak (``cross_silo/secagg_soak.py``):

- **Ring sizing** (:func:`ring_bits_for`): the masking ring is sized to the
  quantizer's value width plus the cohort's carry headroom, so the modular
  sum of every upload is EXACT — ``streaming masked sum == exact unmasked
  sum`` is an integer identity, not an FMA-tolerance claim.
- **Minimal wire dtypes** (:func:`pack_ring`/:func:`unpack_ring`): masked
  field elements ship as the smallest unsigned dtype that holds the ring
  (u8/u16/u32, plus a packed 3-byte form for rings up to 2^24) instead of
  the historical int64 — dense+mask drops 8 -> 4 bytes/element for free.
- **Quantize-then-mask** (:func:`quantize_stochastic_int8`): the qsgd8
  composition.  Per-block adaptive scales (the plain-wire qsgd8 codec) are
  incompatible with additive masking — the server would need each client's
  scales to unscale a masked SUM it cannot decompose — so the secure form
  uses qsgd8's stochastic-rounding grid at a FIXED, config-shared scale
  (``2^-frac_bits``), which keeps the sum exact in the ring and the upload
  at int8 width.  ``comm_compression=qsgd8`` and SecAgg stack instead of
  excluding each other.
- :class:`StreamingMaskedSum`: the server-side fold.  Wraps the
  :class:`~fedml_tpu.parallel.stream_fold.FieldStreamAccumulator` (the
  field-domain sibling of the f32 streaming accumulator every other fold
  rides) and tracks the peak-buffered bound.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from .field import DEFAULT_PRIME, dequantize_from_field

__all__ = [
    "DENSE_RING_BITS",
    "MaskedRing",
    "StreamingMaskedSum",
    "mask_vector",
    "pack_ring",
    "quantize_stochastic_int8",
    "ring_bits_for",
    "ring_for",
    "ring_mask",
    "unmask_ring_total",
    "unpack_ring",
]

#: the dense (full-precision fixed-point) path keeps the historical prime
#: field M31 — its quantize/unmask math stays bit-identical to the buffered
#: protocol; only the wire width shrinks (int64 -> u32)
DENSE_RING_BITS = 31

#: int8-grid value width of the qsgd8 composition (values in [-127, 127])
Q8_VALUE_BITS = 8


def ring_bits_for(value_bits: int, n_clients: int) -> int:
    """Bits of the power-of-two masking ring for sums of ``n_clients``
    values of ``value_bits`` signed width: the true sum must stay strictly
    inside (-ring/2, ring/2) so the centered decode is exact."""
    return value_bits + int(math.ceil(math.log2(max(int(n_clients), 1)))) + 1


class MaskedRing:
    """One masking ring: modulus, wire width, and the quantizer it carries.

    ``codec`` is ``"dense"`` (fixed-point at ``frac_bits`` over the M31
    prime field — the historical math) or ``"qsgd8"`` (stochastic int8 grid
    at ``frac_bits`` over a cohort-sized power-of-two ring)."""

    __slots__ = ("codec", "modulus", "bits", "frac_bits", "n_clients")

    def __init__(self, codec: str, n_clients: int, frac_bits: int):
        self.codec = str(codec)
        self.n_clients = int(n_clients)
        self.frac_bits = int(frac_bits)
        if self.codec == "dense":
            self.bits = DENSE_RING_BITS
            self.modulus = DEFAULT_PRIME
        elif self.codec == "qsgd8":
            self.bits = ring_bits_for(Q8_VALUE_BITS, n_clients)
            self.modulus = 1 << self.bits
        else:
            raise ValueError(f"unknown secagg stream codec {self.codec!r}")

    def meta(self, length: int) -> dict:
        """Control-plane description of an upload (cross-checked server-side
        so a ring mismatch is a loud reject, not silent corruption)."""
        return {"codec": self.codec, "ring_bits": int(self.bits),
                "frac_bits": int(self.frac_bits), "length": int(length)}

    def matches(self, meta: dict) -> bool:
        return (meta.get("codec") == self.codec
                and int(meta.get("ring_bits", -1)) == self.bits
                and int(meta.get("frac_bits", -1)) == self.frac_bits)

    def wire_nbytes(self, length: int) -> int:
        return length * (1 if self.bits <= 8 else
                         2 if self.bits <= 16 else
                         3 if self.bits <= 24 else 4)


def ring_for(codec: Optional[str], n_clients: int, *, q_bits: int,
             q8_frac_bits: int) -> MaskedRing:
    """The ring a config implies: ``comm_compression=qsgd8`` selects the
    quantize-then-mask composition, anything else the dense fixed-point
    field (``q_bits`` fractional bits, the historical ``secagg_q_bits``)."""
    if codec == "qsgd8":
        return MaskedRing("qsgd8", n_clients, q8_frac_bits)
    return MaskedRing("dense", n_clients, q_bits)


def quantize_stochastic_int8(flat: np.ndarray, frac_bits: int, seed) -> np.ndarray:
    """f32 vector -> int8-range integers on the fixed grid ``2^-frac_bits``
    with unbiased stochastic rounding (``E[floor(x*s + u)] = x*s`` for
    ``u ~ U[0,1)`` — the same rounding rule as the qsgd8 Pallas kernel,
    host-side and at a shared scale so masked sums stay decodable).
    Values beyond the grid clip to [-127, 127]."""
    scaled = np.asarray(flat, np.float64) * float(1 << int(frac_bits))
    u = np.random.default_rng(seed).random(scaled.shape)
    q = np.floor(scaled + u)
    return np.clip(q, -127, 127).astype(np.int64)


def dequantize_sum(total_signed: np.ndarray, ring: MaskedRing,
                   n_summands: int) -> np.ndarray:
    """Centered ring total -> float mean over ``n_summands`` uploads."""
    return (dequantize_from_field(total_signed, n_summands, p=ring.modulus,
                                  bits=ring.frac_bits)
            / max(int(n_summands), 1)).astype(np.float64)


# -- mask expansion -----------------------------------------------------------
#
# The legacy buffer-all protocol expands masks with MT19937
# (``shamir.pairwise_mask``) and keeps doing so.  The streaming protocol
# derives the SAME per-round seeds (the secrets Shamir protects) but expands
# them through PCG64: the server regenerates O(cohort) mask vectors at
# finalize, and MT19937 state setup makes that the finalize wall (~4x
# slower than PCG64 at 4k elements).  Both ends of a run are gated by the
# same ``secagg_stream`` flag, so the PRG is a protocol constant, never
# mixed within a round.

def ring_mask(seed: int, d: int, modulus: int) -> np.ndarray:
    """Deterministic mask vector over the ring from a shared seed (the
    streaming protocol's PRG — see note above)."""
    return np.random.default_rng(int(seed) % (2**31)).integers(
        0, int(modulus), size=d, dtype=np.int64)


def mask_vector(x_field: np.ndarray, client_id: int, peer_seeds: dict,
                self_seed: int, modulus: int) -> np.ndarray:
    """The SecAgg masking equation over the ring (streaming form of
    ``shamir.masked_input``): ``y = x + PRG(b) + sum_{j>i} PRG(s_ij)
    - sum_{j<i} PRG(s_ij)  (mod ring)``."""
    d = len(x_field)
    y = (np.asarray(x_field, np.int64) + ring_mask(self_seed, d, modulus)) % modulus
    for j, s in peer_seeds.items():
        m = ring_mask(s, d, modulus)
        if j > client_id:
            y = (y + m) % modulus
        elif j < client_id:
            y = (y - m) % modulus
    return y


def unmask_ring_total(total: np.ndarray, self_seeds: dict,
                      dropped_pair_seeds: dict, modulus: int) -> np.ndarray:
    """Unmask a pre-summed ring total (streaming form of
    ``shamir.unmask_streamed``, same sign conventions)."""
    total = np.asarray(total, np.int64) % modulus
    d = total.shape[0]
    for _u, b in self_seeds.items():
        total = (total - ring_mask(b, d, modulus)) % modulus
    for (i, j), s in dropped_pair_seeds.items():
        m = ring_mask(s, d, modulus)
        # survivor j's upload carries the uncancelled half of the (i, j)
        # pair mask: for j > i it added -m, for j < i it added +m
        if j > i:
            total = (total + m) % modulus
        else:
            total = (total - m) % modulus
    return total


# -- wire packing -------------------------------------------------------------

def pack_ring(vec: np.ndarray, bits: int) -> np.ndarray:
    """Field elements in [0, 2^bits) -> the smallest little-endian unsigned
    wire array that holds them (u8 / u16 / packed-3-byte / u32)."""
    v = np.asarray(vec, np.int64)
    if bits <= 8:
        return v.astype("<u1")
    if bits <= 16:
        return v.astype("<u2")
    if bits <= 24:
        quads = np.ascontiguousarray(v.astype("<u4")).view(np.uint8)
        return np.ascontiguousarray(quads.reshape(-1, 4)[:, :3]).reshape(-1)
    if bits <= 32:
        return v.astype("<u4")
    raise ValueError(f"ring of {bits} bits exceeds the 32-bit wire limit")


def unpack_ring(raw: np.ndarray, bits: int, length: int) -> np.ndarray:
    """Inverse of :func:`pack_ring` -> int64 field elements."""
    a = np.asarray(raw)
    if bits <= 8 or bits <= 16:
        out = a.view(f"<u{1 if bits <= 8 else 2}").astype(np.int64)
    elif bits <= 24:
        trip = a.view(np.uint8).reshape(-1, 3)
        quads = np.zeros((trip.shape[0], 4), np.uint8)
        quads[:, :3] = trip
        out = quads.reshape(-1).view("<u4").astype(np.int64)
    elif bits <= 32:
        out = a.view("<u4").astype(np.int64)
    else:
        raise ValueError(f"ring of {bits} bits exceeds the 32-bit wire limit")
    if out.shape[0] != int(length):
        raise ValueError(f"packed length {out.shape[0]} != declared {length}")
    return out


# -- the server-side streaming fold -------------------------------------------

class StreamingMaskedSum:
    """Fold masked field vectors one at a time; unmask ONCE at finalize.

    Rides the :class:`~fedml_tpu.parallel.stream_fold.FieldStreamAccumulator`
    — lazy modular reduction (int64 headroom carries ~2^63/modulus folds
    before a reduce, far past any cohort), so a fold costs one vector add.
    ``peak_buffered`` counts what the <=2 acceptance bound tracks: the
    running total plus the one in-flight upload being folded."""

    def __init__(self, dim: int, ring: MaskedRing):
        from ...parallel.stream_fold import FieldStreamAccumulator

        self.ring = ring
        self.dim = int(dim)
        self._acc = FieldStreamAccumulator(
            [np.zeros(self.dim, np.int64)], ring.modulus)
        self.folded = 0
        self.peak_buffered = 0

    def fold(self, vec: np.ndarray) -> None:
        v = np.asarray(vec, np.int64)
        if v.shape != (self.dim,):
            raise ValueError(f"masked vector shape {v.shape} != ({self.dim},)")
        self.peak_buffered = max(self.peak_buffered,
                                 (1 if self.folded else 0) + 1)
        self._acc.fold_leaf(0, v)
        self.folded += 1

    def masked_total(self) -> np.ndarray:
        """The reduced field total of everything folded so far."""
        return self._acc.host_sums()[0]

    def finalize(self, self_seeds: dict, dropped_pair_seeds: dict) -> np.ndarray:
        """Unmask the streamed total (centered signed int64): subtract every
        survivor's reconstructed self-mask, cancel the orphaned halves of
        dropped clients' pair masks — the same reconstruction the buffered
        protocol ran, minus the cohort-sized buffer."""
        total = unmask_ring_total(self.masked_total(), self_seeds,
                                  dropped_pair_seeds, self.ring.modulus)
        half = self.ring.modulus // 2
        return np.where(total > half, total - self.ring.modulus, total)
