"""LightSecAgg — Lagrange-coded one-shot mask reconstruction.

Parity with ``core/mpc/lightsecagg.py``: each client encodes its random mask
with Lagrange coded computing (``mask_encoding`` :97) and distributes shares;
to unmask, each surviving client sends ONE aggregate encoded mask
(``compute_aggregate_encoded_mask`` :126); the server interpolates the sum of
masks from any U survivors and subtracts it (dropout-tolerant with threshold
T, unlike pairwise-mask SecAgg which needs per-dropout recovery).

Shapes: model vector of length d is padded to d' divisible by (U - T);
mask z_i ~ F_p^{d'}; split into U-T chunks; append T random chunks; encode at
N evaluation points via Lagrange coefficients (one int64 matmul per client).
"""

from __future__ import annotations

import numpy as np

from .field import DEFAULT_PRIME, gen_lagrange_coeffs, mod_inverse


class LightSecAggProtocol:
    def __init__(self, n_clients: int, privacy_t: int, target_u: int, p: int = DEFAULT_PRIME, seed: int = 0):
        """n_clients=N, privacy threshold T (collusion tolerance),
        reconstruction target U (need >= U survivors), T < U <= N."""
        assert privacy_t < target_u <= n_clients
        self.n = n_clients
        self.t = privacy_t
        self.u = target_u
        self.p = p
        # SeedSequence accepts arbitrarily large entropy ints (the protocol
        # layer feeds 256-bit OS entropy so mask streams can't be
        # brute-forced); RandomState alone caps seeds at 2^32
        self.rng = np.random.RandomState(np.random.SeedSequence(seed).generate_state(8))
        # evaluation points: alpha_j for interpolation targets (U-T + T chunks),
        # beta_i for the N clients — all distinct, nonzero.
        self.alphas = np.arange(1, self.u + 1, dtype=np.int64)
        self.betas = np.arange(self.u + 1, self.u + self.n + 1, dtype=np.int64)

    def pad_len(self, d: int) -> int:
        k = self.u - self.t
        return ((d + k - 1) // k) * k

    def gen_mask(self, d: int) -> np.ndarray:
        return self.rng.randint(0, self.p, size=self.pad_len(d), dtype=np.int64)

    def encode_mask(self, mask: np.ndarray, noise: np.ndarray = None) -> np.ndarray:
        """(N, d'/(U-T)) encoded sub-masks, one row per receiving client —
        reference ``mask_encoding``.  ``noise`` (the T privacy chunks) is
        drawn from the protocol RNG unless given explicitly (the C++ kernel
        conformance tests inject it to make the encode deterministic)."""
        k = self.u - self.t
        chunks = mask.reshape(k, -1)  # (U-T, s)
        if noise is None:
            noise = self.rng.randint(0, self.p, size=(self.t, chunks.shape[1]), dtype=np.int64)
        else:
            noise = np.asarray(noise, dtype=np.int64).reshape(self.t, chunks.shape[1])
        extended = np.concatenate([chunks, noise], axis=0)  # (U, s)
        W = gen_lagrange_coeffs(self.betas, self.alphas, self.p)  # (N, U)
        # int64 modular matmul: accumulate mod p chunk-wise to avoid overflow
        out = np.zeros((self.n, chunks.shape[1]), dtype=np.int64)
        for j in range(self.u):
            out = (out + W[:, j : j + 1] * extended[j : j + 1, :]) % self.p
        return out

    @staticmethod
    def aggregate_encoded_masks(shares: list[np.ndarray]) -> np.ndarray:
        """Each surviving client sums the encoded sub-masks it holds —
        reference ``compute_aggregate_encoded_mask``."""
        out = shares[0].copy()
        for s in shares[1:]:
            out = (out + s) % DEFAULT_PRIME
        return out

    def decode_aggregate_mask(self, agg_shares: dict[int, np.ndarray], d_pad: int) -> np.ndarray:
        """Server: interpolate sum-of-masks from >= U survivors' aggregates —
        reference ``aggregate_models_in_finite`` decoding path."""
        survivors = sorted(agg_shares.keys())[: self.u]
        assert len(survivors) >= self.u, f"need {self.u} survivors, have {len(agg_shares)}"
        eval_pts = self.betas[np.array(survivors)]
        W = gen_lagrange_coeffs(self.alphas[: self.u - self.t], eval_pts, self.p)  # (U-T, U)
        s = agg_shares[survivors[0]].shape[0]
        chunks = np.zeros((self.u - self.t, s), dtype=np.int64)
        for col, cid in enumerate(survivors):
            chunks = (chunks + W[:, col : col + 1] * agg_shares[cid][None, :]) % self.p
        return chunks.reshape(-1)[:d_pad]


def secure_aggregate(vectors: list[np.ndarray], protocol: LightSecAggProtocol,
                     dropout: set[int] = frozenset()) -> np.ndarray:
    """End-to-end round over quantized field vectors: mask, share, drop some
    clients, reconstruct the sum of SURVIVORS' vectors.  Returns field sum."""
    n = protocol.n
    d = len(vectors[0])
    dp = protocol.pad_len(d)
    masks = [protocol.gen_mask(d) for _ in range(n)]
    encoded = [protocol.encode_mask(m) for m in masks]  # encoded[i][j] -> share of i's mask held by j
    survivors = [i for i in range(n) if i not in dropout]
    # each client uploads masked vector (only survivors')
    masked = {
        i: (np.pad(vectors[i], (0, dp - d)) + masks[i]) % protocol.p for i in survivors
    }
    # surviving clients aggregate the encoded sub-masks of *surviving* sources
    agg_shares = {
        j: LightSecAggProtocol.aggregate_encoded_masks([encoded[i][j] for i in survivors])
        for j in survivors
    }
    mask_sum = protocol.decode_aggregate_mask(agg_shares, dp)
    total = np.zeros(dp, dtype=np.int64)
    for i in survivors:
        total = (total + masked[i]) % protocol.p
    return (total - mask_sum) % protocol.p
