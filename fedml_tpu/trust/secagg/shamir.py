"""Shamir secret sharing + pairwise-mask SecAgg math.

Parity with ``core/mpc/secagg.py`` (the math behind ``cross_silo/secagg``):
t-of-n Shamir shares over F_p, pairwise masks derived from shared seeds, and
mask reconstruction for dropped clients.
"""

from __future__ import annotations

import numpy as np

from .field import DEFAULT_PRIME, mod_inverse


def shamir_share(secret: int, n: int, t: int, rng: np.random.RandomState, p: int = DEFAULT_PRIME):
    """Split ``secret`` into n shares, any t reconstruct.  Returns
    [(x_i, y_i)] with x_i = 1..n."""
    coeffs = [int(secret) % p] + [int(rng.randint(0, p)) for _ in range(t - 1)]
    shares = []
    for x in range(1, n + 1):
        y = 0
        for a in reversed(coeffs):
            y = (y * x + a) % p
        shares.append((x, y))
    return shares


def shamir_reconstruct(shares, p: int = DEFAULT_PRIME) -> int:
    """Lagrange interpolation at 0 from >= t shares."""
    total = 0
    for i, (xi, yi) in enumerate(shares):
        num, den = 1, 1
        for j, (xj, _) in enumerate(shares):
            if i != j:
                num = (num * (-xj % p)) % p
                den = (den * ((xi - xj) % p)) % p
        total = (total + yi * num * mod_inverse(den, p)) % p
    return int(total)


def pairwise_mask(seed: int, d: int, p: int = DEFAULT_PRIME) -> np.ndarray:
    """Deterministic mask vector from a shared pairwise seed (PRG role of the
    reference's key-agreement seeds)."""
    return np.random.RandomState(seed % (2**31)).randint(0, p, size=d, dtype=np.int64)


def masked_input(x_field: np.ndarray, client_id: int, peer_seeds: dict[int, int], self_seed: int,
                 p: int = DEFAULT_PRIME) -> np.ndarray:
    """y_i = x_i + PRG(b_i) + sum_{j>i} PRG(s_ij) - sum_{j<i} PRG(s_ij)  (mod p)
    — the SecAgg masking equation (secagg.py)."""
    d = len(x_field)
    y = (x_field + pairwise_mask(self_seed, d, p)) % p
    for j, s in peer_seeds.items():
        m = pairwise_mask(s, d, p)
        if j > client_id:
            y = (y + m) % p
        elif j < client_id:
            y = (y - m) % p
    return y


def unmask_sum(masked: dict[int, np.ndarray], self_seeds: dict[int, int],
               dropped_pair_seeds: dict[tuple[int, int], int], p: int = DEFAULT_PRIME) -> np.ndarray:
    """Server: sum survivors' masked inputs, remove survivors' self-masks
    (revealed via Shamir) and dropped clients' pairwise masks."""
    ids = sorted(masked.keys())
    d = len(next(iter(masked.values())))
    total = np.zeros(d, dtype=np.int64)
    for i in ids:
        total = (total + masked[i]) % p
    return unmask_streamed(total, self_seeds, dropped_pair_seeds, p)


def unmask_streamed(total: np.ndarray, self_seeds: dict[int, int],
                    dropped_pair_seeds: dict[tuple[int, int], int],
                    p: int = DEFAULT_PRIME) -> np.ndarray:
    """Unmask a PRE-SUMMED field total: the streaming-fold form of
    :func:`unmask_sum` — the masked inputs folded one at a time into
    ``total`` as they arrived, so only the seed reconstruction (tiny
    scalars) happens at finalize, never a cohort-sized buffer."""
    total = np.asarray(total, np.int64) % p
    d = total.shape[0]
    for i, b in self_seeds.items():
        total = (total - pairwise_mask(b, d, p)) % p
    for (i, j), s in dropped_pair_seeds.items():
        m = pairwise_mask(s, d, p)
        # survivor j's masked input carries the uncancelled half of the (i, j)
        # pair mask: for j > i it added -m (peer i < j), for j < i it added +m
        if j > i:
            total = (total + m) % p
        else:
            total = (total - m) % p
    return total
