"""Finite-field primitives for secure aggregation.

Parity with the modular arithmetic in ``core/mpc/lightsecagg.py``
(``modInverse``-style inverses, Lagrange coefficient generation) and its C++
mirror ``android/fedmlsdk/MobileNN/src/security/LightSecAgg.cpp`` (the only
real native compute in the reference — SURVEY.md §2.13).

SURVEY.md §7 hard part 5: finite-field modular ops don't map to bf16 matmuls,
but int64 modular arithmetic in JAX/numpy is exact and fast enough (mask
encode/decode is O(model_size * clients), bandwidth-bound).  The prime is
< 2^31 so products fit in int64 without overflow.
"""

from __future__ import annotations

import numpy as np

DEFAULT_PRIME = 2**31 - 1  # Mersenne prime M31


def mod_pow(base: int, exp: int, p: int = DEFAULT_PRIME) -> int:
    return pow(int(base), int(exp), p)


def mod_inverse(a: int, p: int = DEFAULT_PRIME) -> int:
    """Fermat inverse (p prime) — reference ``modInverse`` (LightSecAgg.cpp)."""
    return pow(int(a) % p, p - 2, p)


def mod_inverse_vec(a: np.ndarray, p: int = DEFAULT_PRIME) -> np.ndarray:
    return np.array([mod_inverse(int(x), p) for x in np.atleast_1d(a)], dtype=np.int64)


def gen_lagrange_coeffs(eval_points: np.ndarray, interp_points: np.ndarray, p: int = DEFAULT_PRIME) -> np.ndarray:
    """(len(eval), len(interp)) Lagrange basis coefficients over F_p —
    reference ``gen_Lagrange_coeffs`` (LightSecAgg.cpp / lightsecagg.py:41).

    coeff[i, j] = prod_{k != j} (e_i - t_k) / (t_j - t_k)  (mod p)
    """
    ev = np.asarray(eval_points, dtype=np.int64) % p
    tp = np.asarray(interp_points, dtype=np.int64) % p
    ne, nt = len(ev), len(tp)
    out = np.zeros((ne, nt), dtype=np.int64)
    for j in range(nt):
        den = 1
        for k in range(nt):
            if k != j:
                den = (den * ((tp[j] - tp[k]) % p)) % p
        den_inv = mod_inverse(den, p)
        for i in range(ne):
            num = 1
            for k in range(nt):
                if k != j:
                    num = (num * ((ev[i] - tp[k]) % p)) % p
            out[i, j] = (num * den_inv) % p
    return out


def quantize_to_field(x: np.ndarray, p: int = DEFAULT_PRIME, bits: int = 16) -> np.ndarray:
    """Float -> field element: fixed-point with 2^bits scale, negatives wrap
    mod p (reference ``my_pk_model_to_finite`` transforms, lightsecagg.py:164-193)."""
    scale = float(2**bits)
    q = np.round(np.asarray(x, dtype=np.float64) * scale).astype(np.int64)
    return np.mod(q, p)


def dequantize_from_field(q: np.ndarray, n_summands: int, p: int = DEFAULT_PRIME, bits: int = 16) -> np.ndarray:
    """Field element -> float, interpreting values > (p - margin)/2 as negative.
    ``n_summands`` bounds the accumulated negative wrap."""
    q = np.asarray(q, dtype=np.int64) % p
    half = p // 2
    signed = np.where(q > half, q - p, q)
    return signed.astype(np.float64) / float(2**bits)
