"""Anomaly-detection defenses: FoolsGold, 3-sigma family, outlier detection,
residual reweighting, cross-round consistency.

Reference: ``core/security/defense/foolsgold_defense.py``,
``three_sigma_defense.py`` (+ ``three_sigma_geomedian_defense.py``,
``three_sigma_krum_defense.py``), ``outlier_detection.py``,
``RFA_defense.py``-adjacent ``residual_reweight*``, ``crossround_defense.py``.
Each is vectorized over the (m, d) update matrix.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import Defense, pairwise_sq_dists, weighted_mean


class FoolsGoldDefense(Defense):
    """FoolsGold: down-weight clients whose updates are too similar (sybils).

    Cosine-similarity logic of ``foolsgold_defense.py:fools_gold_score``:
    cs_ij = cosine sims; v_i = max_j cs_ij; rescale, clamp, logit.
    The reference accumulates historical gradients; this stateless variant
    uses the current round (the engine can thread history later).
    """

    name = "foolsgold"

    def before(self, updates, weights, global_flat):
        m = updates.shape[0]
        norm = jnp.linalg.norm(updates, axis=1, keepdims=True)
        un = updates / jnp.maximum(norm, 1e-12)
        cs = un @ un.T - jnp.eye(m)
        v = jnp.max(cs, axis=1)  # max similarity per client
        # pardoning: scale cs rows by v_i/v_j asymmetry
        scale = jnp.minimum(1.0, v[:, None] / jnp.maximum(v[None, :], 1e-12))
        cs = cs * scale
        alpha = 1.0 - jnp.max(cs, axis=1)
        alpha = alpha / jnp.maximum(jnp.max(alpha), 1e-12)
        alpha = jnp.clip(alpha, 1e-6, 1 - 1e-6)
        wv = jnp.log(alpha / (1 - alpha)) + 0.5
        wv = jnp.clip(wv, 0.0, 1.0)
        return updates, weights * wv


class ThreeSigmaDefense(Defense):
    """3-sigma: score clients by distance to a robust center (coordinate
    median); zero-weight those beyond k sigma (three_sigma_defense.py)."""

    name = "three_sigma"

    def __init__(self, cfg=None, k: float = 3.0):
        super().__init__(cfg)
        self.k = getattr(cfg, "outlier_detection_k", k) if cfg else k

    def center(self, updates, weights):
        return jnp.median(updates, axis=0)

    def before(self, updates, weights, global_flat):
        c = self.center(updates, weights)
        d = jnp.linalg.norm(updates - c[None, :], axis=1)
        mu, sigma = jnp.mean(d), jnp.std(d) + 1e-12
        keep = (d <= mu + self.k * sigma).astype(jnp.float32)
        return updates, weights * keep


class ThreeSigmaGeoMedianDefense(ThreeSigmaDefense):
    """Variant scoring against the geometric median (three_sigma_geomedian)."""

    name = "three_sigma_geomedian"

    def center(self, updates, weights, iters: int = 8):
        w = jnp.ones(updates.shape[0]) / updates.shape[0]
        z = w @ updates

        def step(z, _):
            dist = jnp.sqrt(jnp.sum((updates - z[None, :]) ** 2, axis=1) + 1e-6)
            a = w / dist
            a = a / jnp.maximum(a.sum(), 1e-12)
            return a @ updates, None

        z, _ = jax.lax.scan(step, z, None, length=iters)
        return z


class ThreeSigmaKrumDefense(ThreeSigmaDefense):
    """Variant scoring against the Krum-selected client (three_sigma_krum)."""

    name = "three_sigma_krum"

    def center(self, updates, weights):
        from .robust_agg import krum_scores

        scores = krum_scores(updates, byzantine_num=1)
        best = jnp.argmin(scores)
        return updates[best]


class OutlierDetectionDefense(Defense):
    """Per-coordinate z-score outlier masking (outlier_detection.py): replace
    entries deviating > k sigma from the coordinate mean with the coordinate
    median before averaging."""

    name = "outlier_detection"

    def __init__(self, cfg=None, k: float = 3.0):
        super().__init__(cfg)
        self.k = getattr(cfg, "outlier_detection_k", k) if cfg else k

    def before(self, updates, weights, global_flat):
        mu = jnp.mean(updates, axis=0, keepdims=True)
        sd = jnp.std(updates, axis=0, keepdims=True) + 1e-12
        med = jnp.median(updates, axis=0, keepdims=True)
        mask = jnp.abs(updates - mu) <= self.k * sd
        return jnp.where(mask, updates, med), weights


class ResidualReweightDefense(Defense):
    """IRLS residual-based reweighting (residual_reweighting): weight clients
    by a Huber-style function of their residual to the coordinate median."""

    name = "residual_reweight"

    def __init__(self, cfg=None, delta: float = 1.0):
        super().__init__(cfg)
        self.delta = delta

    def before(self, updates, weights, global_flat):
        med = jnp.median(updates, axis=0)
        r = jnp.linalg.norm(updates - med[None, :], axis=1)
        r = r / jnp.maximum(jnp.median(r), 1e-12)
        wgt = jnp.where(r <= self.delta, 1.0, self.delta / r)
        return updates, weights * wgt


class CrossRoundDefense(Defense):
    """Cross-round consistency (crossround_defense.py): compare each client's
    update direction with the previous global movement; down-weight clients
    whose cosine to the last round's aggregate delta is negative."""

    name = "cross_round"

    def __init__(self, cfg=None):
        super().__init__(cfg)
        self._prev_delta = None  # set by engine between rounds (host-side)

    def set_history(self, prev_delta_flat):
        self._prev_delta = prev_delta_flat

    def before(self, updates, weights, global_flat):
        if self._prev_delta is None:
            return updates, weights
        delta = updates - global_flat[None, :]
        pd = self._prev_delta / jnp.maximum(jnp.linalg.norm(self._prev_delta), 1e-12)
        cos = (delta @ pd) / jnp.maximum(jnp.linalg.norm(delta, axis=1), 1e-12)
        keep = (cos >= 0.0).astype(jnp.float32)
        # never discard everyone
        keep = jnp.where(keep.sum() > 0, keep, jnp.ones_like(keep))
        return updates, weights * keep
