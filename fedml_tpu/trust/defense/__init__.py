"""Defense registry.

Parity with ``FedMLDefender`` dispatch (``core/security/fedml_defender.py:63-91``):
config key ``defense_type`` selects the defense; the engine applies its three
hooks around aggregation.  All defenses are pure functions over the stacked
(m, d) client-update matrix (see ``base.py``).
"""

from __future__ import annotations

from .base import Defense, weighted_mean
from .clipping import (
    CClipDefense,
    CRFLDefense,
    NormDiffClippingDefense,
    RobustLearningRateDefense,
    SLSGDDefense,
    WeakDPDefense,
)
from .anomaly import (
    CrossRoundDefense,
    FoolsGoldDefense,
    OutlierDetectionDefense,
    ResidualReweightDefense,
    ThreeSigmaDefense,
    ThreeSigmaGeoMedianDefense,
    ThreeSigmaKrumDefense,
)
from .soteria import SoteriaDefense, WBCDefense, soteria_mask, soteria_sensitivity
from .robust_agg import (
    BulyanDefense,
    CoordinateWiseMedianDefense,
    GeometricMedianDefense,
    KrumDefense,
    MultiKrumDefense,
    TrimmedMeanDefense,
)

_REGISTRY = {
    "krum": KrumDefense,
    "multikrum": MultiKrumDefense,
    "geometric_median": GeometricMedianDefense,
    "RFA": GeometricMedianDefense,  # reference alias
    "coordinate_median": CoordinateWiseMedianDefense,
    "coordinate_wise_median": CoordinateWiseMedianDefense,
    "trimmed_mean": TrimmedMeanDefense,
    "coordinate_wise_trimmed_mean": TrimmedMeanDefense,
    "bulyan": BulyanDefense,
    "norm_diff_clipping": NormDiffClippingDefense,
    "cclip": CClipDefense,
    "weak_dp": WeakDPDefense,
    "slsgd": SLSGDDefense,
    "robust_learning_rate": RobustLearningRateDefense,
    "crfl": CRFLDefense,
    "foolsgold": FoolsGoldDefense,
    "three_sigma": ThreeSigmaDefense,
    "three_sigma_geomedian": ThreeSigmaGeoMedianDefense,
    "three_sigma_krum": ThreeSigmaKrumDefense,
    "outlier_detection": OutlierDetectionDefense,
    "residual_reweight": ResidualReweightDefense,
    "cross_round": CrossRoundDefense,
    "soteria": SoteriaDefense,
    "wbc": WBCDefense,
}


def names() -> list[str]:
    return sorted(_REGISTRY)


def create(cfg) -> Defense:
    dt = getattr(cfg, "defense_type", "")
    try:
        return _REGISTRY[dt](cfg)
    except KeyError:
        raise ValueError(f"unknown defense_type {dt!r}; known: {names()}") from None
