"""Soteria + WBC client-side perturbation defenses.

Soteria (reference ``core/security/defense/soteria_defense.py:28``, Sun et
al. CVPR'21): against gradient-leakage (DLG) attacks, prune the fraction of
the feature-layer representation gradient with the smallest sensitivity
ratio ||d r_f / d x|| / |r_f| — the coordinates an attacker relies on most
per unit of useful signal.  The reference computes the jacobian column-by-
column with a python loop of ``backward`` calls; here it is ONE
``jax.jacrev`` (the whole sensitivity matrix in a single traced pass).

WBC (reference ``wbc_defense.py:25``, "white blood cell"): perturb update
coordinates with Laplace noise wherever the update changed LITTLE since the
previous round (|delta - prev_delta| <= |noise|) — stable coordinates carry
the memorized information an inverter can exploit; fast-moving ones are left
alone so learning proceeds.  The reference implements per-client gradient
history (stubbed in places); the aggregation-frame adaptation here uses the
previous round's global delta as the history signal, threaded through the
engine's existing defense-history slot.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.flags import cfg_extra
from .base import Defense


def soteria_sensitivity(model, variables, x, feature_fn=None):
    """(features,) sensitivity ||d r_f/d x|| / |r_f| for the representation
    layer.  ``feature_fn(variables, x) -> (batch, features)`` defaults to the
    model's penultimate activations via ``model.apply(..., train=False)`` on
    a model whose output IS the representation (LR: the logits themselves)."""
    if feature_fn is None:
        def feature_fn(v, xx):
            return model.apply(v, xx, train=False)

    def flat_features(xx):
        return feature_fn(variables, xx[None])[0]

    r = flat_features(x)
    jac = jax.jacrev(flat_features)(x)           # (features, *x.shape)
    grad_norms = jnp.sqrt(jnp.sum(jac.reshape(jac.shape[0], -1) ** 2, axis=1))
    return grad_norms / jnp.maximum(jnp.abs(r), 1e-12)


def soteria_mask(model, variables, x, percentile: float = 1.0, feature_fn=None):
    """0/1 mask over the feature dimension pruning the lowest-sensitivity
    ``percentile`` percent (reference prunes with np.percentile at 1)."""
    sens = soteria_sensitivity(model, variables, x, feature_fn)
    thresh = jnp.percentile(sens, percentile)
    return (sens >= thresh).astype(jnp.float32), sens


class SoteriaDefense(Defense):
    """Aggregation-frame adaptation: per client, zero the ``percentile``
    percent smallest-|delta| coordinates of the update (magnitude stands in
    for the sensitivity ratio, which needs the client's model+data — use
    ``soteria_mask`` directly for the faithful client-side DLG defense)."""

    name = "soteria"

    def __init__(self, cfg=None):
        super().__init__(cfg)
        self.percentile = float(cfg_extra(cfg, "soteria_percentile"))

    def before(self, updates, weights, global_flat):
        delta = updates - global_flat[None, :]
        thresh = jnp.percentile(jnp.abs(delta), self.percentile, axis=1, keepdims=True)
        pruned = jnp.where(jnp.abs(delta) >= thresh, delta, 0.0)
        return global_flat[None, :] + pruned, weights


class WBCDefense(Defense):
    name = "wbc"

    def __init__(self, cfg=None):
        super().__init__(cfg)
        self.strength = float(cfg_extra(cfg, "wbc_pert_strength"))
        self.lr = float(cfg_extra(cfg, "wbc_lr"))
        self._prev_delta = None
        self._key = jax.random.PRNGKey(0)

    def set_key(self, key):
        self._key = key

    def set_history(self, prev_delta_flat):
        self._prev_delta = prev_delta_flat

    def before(self, updates, weights, global_flat):
        delta = updates - global_flat[None, :]
        prev = self._prev_delta if self._prev_delta is not None else jnp.zeros_like(global_flat)
        pert = jax.random.laplace(self._key, updates.shape) * self.strength
        # perturb only where the round-over-round change is smaller than the
        # drawn noise (reference: np.where(|grad_diff| > |pert|, 0, pert))
        pert = jnp.where(jnp.abs(delta - prev[None, :]) > jnp.abs(pert), 0.0, pert)
        return updates + pert * self.lr, weights
