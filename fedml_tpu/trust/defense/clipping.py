"""Clipping / noise defenses: norm-diff clipping, centered clip, weak DP,
SLSGD, robust learning rate, CRFL.

Reference: ``core/security/defense/norm_diff_clipping_defense.py``,
``cclip_defense.py``, ``weak_dp_defense.py``, ``slsgd_defense.py``,
``robust_learning_rate_defense.py``, ``crfl_defense.py``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import Defense, weighted_mean


class NormDiffClippingDefense(Defense):
    """Clip each client's update delta (w_i - w_global) to a norm bound
    (norm_diff_clipping_defense.py)."""

    name = "norm_diff_clipping"

    def __init__(self, cfg=None, norm_bound: float = 5.0):
        super().__init__(cfg)
        self.norm_bound = getattr(cfg, "norm_bound", norm_bound) if cfg else norm_bound

    def before(self, updates, weights, global_flat):
        delta = updates - global_flat[None, :]
        norms = jnp.linalg.norm(delta, axis=1, keepdims=True)
        scale = jnp.minimum(1.0, self.norm_bound / jnp.maximum(norms, 1e-12))
        return global_flat[None, :] + delta * scale, weights


class CClipDefense(Defense):
    """Centered clipping (Karimireddy et al.): clip deltas around the previous
    global model with bound tau, then average (cclip_defense.py)."""

    name = "cclip"

    def __init__(self, cfg=None, tau: float = 10.0):
        super().__init__(cfg)
        self.tau = getattr(cfg, "norm_bound", tau) if cfg else tau

    def on_agg(self, updates, weights, global_flat):
        delta = updates - global_flat[None, :]
        norms = jnp.linalg.norm(delta, axis=1, keepdims=True)
        scale = jnp.minimum(1.0, self.tau / jnp.maximum(norms, 1e-12))
        return global_flat + weighted_mean(delta * scale, weights)


class WeakDPDefense(Defense):
    """Weak DP: clip then add small gaussian noise to each update
    (weak_dp_defense.py).  The noise key is derived from the round key the
    engine passes via ``set_key``."""

    name = "weak_dp"

    def __init__(self, cfg=None, norm_bound: float = 5.0, stddev: float = 0.002):
        super().__init__(cfg)
        self.norm_bound = getattr(cfg, "norm_bound", norm_bound) if cfg else norm_bound
        self.stddev = stddev
        self._key = jax.random.PRNGKey(0)

    def set_key(self, key):
        self._key = key

    def before(self, updates, weights, global_flat):
        delta = updates - global_flat[None, :]
        norms = jnp.linalg.norm(delta, axis=1, keepdims=True)
        scale = jnp.minimum(1.0, self.norm_bound / jnp.maximum(norms, 1e-12))
        noise = jax.random.normal(self._key, updates.shape) * self.stddev
        return global_flat[None, :] + delta * scale + noise, weights


class SLSGDDefense(Defense):
    """SLSGD: trimmed-mean aggregate mixed with the previous global:
    w' = (1-a) w + a agg (slsgd_defense.py)."""

    name = "slsgd"

    def __init__(self, cfg=None, alpha: float = 0.5, trim_b: int = 1):
        super().__init__(cfg)
        self.alpha = alpha
        self.trim_b = trim_b

    def on_agg(self, updates, weights, global_flat):
        m = updates.shape[0]
        b = min(self.trim_b, (m - 1) // 2)
        s = jnp.sort(updates, axis=0)
        agg = jnp.mean(s[b : m - b], axis=0) if b > 0 else weighted_mean(updates, weights)
        return (1.0 - self.alpha) * global_flat + self.alpha * agg


class RobustLearningRateDefense(Defense):
    """Robust LR (Ozdayi et al.): per-coordinate, flip the server lr sign
    where fewer than ``theta`` clients agree on the update direction
    (robust_learning_rate_defense.py)."""

    name = "robust_learning_rate"

    def __init__(self, cfg=None, theta: int = 1):
        super().__init__(cfg)
        self.theta = theta

    def on_agg(self, updates, weights, global_flat):
        delta = updates - global_flat[None, :]
        sign_sum = jnp.abs(jnp.sum(jnp.sign(delta), axis=0))
        lr_sign = jnp.where(sign_sum >= self.theta, 1.0, -1.0)
        return global_flat + lr_sign * weighted_mean(delta, weights)


class CRFLDefense(Defense):
    """CRFL (certified robustness): clip the aggregated global to a norm bound
    and add gaussian perturbation after aggregation (crfl_defense.py)."""

    name = "crfl"

    def __init__(self, cfg=None, norm_bound: float = 15.0, stddev: float = 0.002):
        super().__init__(cfg)
        self.norm_bound = getattr(cfg, "norm_bound", norm_bound) if cfg else norm_bound
        self.stddev = stddev
        self._key = jax.random.PRNGKey(0)

    def set_key(self, key):
        self._key = key

    def after(self, new_global_flat, old_global_flat):
        norm = jnp.linalg.norm(new_global_flat)
        clipped = new_global_flat * jnp.minimum(1.0, self.norm_bound / jnp.maximum(norm, 1e-12))
        return clipped + jax.random.normal(self._key, clipped.shape) * self.stddev
