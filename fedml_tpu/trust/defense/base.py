"""Defense pipeline frame.

Reference: ``FedMLDefender`` (``core/security/fedml_defender.py:40``) threads
every defense through three lifecycle hooks around aggregation
(``defend_before_aggregation`` / ``defend_on_aggregation`` /
``defend_after_aggregation``), each consuming a python list of
``(sample_num, state_dict)`` tuples.  Here the same three hooks are pure
functions over the **stacked client-update matrix** ``(m, d)`` (flattened
pytrees, see ``core.pytree.stacked_tree_to_matrix``), so a defense is a few
matmuls/reductions that fuse into the round program — pairwise-distance
defenses (Krum, Bulyan) become one ``U @ U.T`` on the MXU instead of nested
python loops.

Weight semantics: defenses signal "discard client i" by zeroing its weight;
the weighted mean downstream then ignores it — shapes stay static (no boolean
filtering inside jit).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...core import pytree as pt


class Defense:
    """Base: identity at all three hooks.  Subclasses override any subset.

    All methods are pure and jit-traceable.  ``before`` may modify updates
    and/or weights; ``on_agg`` may replace the aggregation entirely (return
    aggregated flat vector); ``after`` may post-process the new global.
    """

    name = "identity"

    def __init__(self, cfg=None):
        self.cfg = cfg

    def before(self, updates: jax.Array, weights: jax.Array, global_flat: jax.Array):
        """(m, d) updates, (m,) weights -> same shapes."""
        return updates, weights

    def on_agg(self, updates: jax.Array, weights: jax.Array, global_flat: jax.Array) -> Optional[jax.Array]:
        """Return (d,) aggregate to REPLACE the weighted mean, or None."""
        return None

    def after(self, new_global_flat: jax.Array, old_global_flat: jax.Array) -> jax.Array:
        return new_global_flat


def weighted_mean(updates: jax.Array, weights: jax.Array) -> jax.Array:
    w = weights / jnp.maximum(weights.sum(), 1e-12)
    return w @ updates


def pairwise_sq_dists(u: jax.Array) -> jax.Array:
    """(m, d) -> (m, m) squared euclidean distances, via one gram matmul."""
    sq = jnp.sum(u * u, axis=1)
    g = u @ u.T
    d2 = sq[:, None] + sq[None, :] - 2.0 * g
    return jnp.maximum(d2, 0.0)
