"""Robust aggregation defenses: Krum/MultiKrum, RFA geometric median,
coordinate-wise median, trimmed mean, Bulyan.

Reference implementations (python loops over state_dict lists):
``core/security/defense/krum_defense.py``, ``geometric_median_defense.py``,
``coordinate_wise_median_defense.py``, ``coordinate_wise_trimmed_mean_defense.py``,
``bulyan_defense.py``.  Here each is dense linear algebra over the stacked
``(m, d)`` update matrix: Krum's pairwise distances are one gram matmul; the
geometric median is a fixed number of Weiszfeld iterations under ``lax.scan``
(compiler-friendly, no data-dependent loop).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import Defense, pairwise_sq_dists, weighted_mean


def krum_scores(updates: jax.Array, byzantine_num: int) -> jax.Array:
    """Krum score: for each client, sum of its m - f - 2 smallest squared
    distances to other clients (lower = more central)."""
    m = updates.shape[0]
    d2 = pairwise_sq_dists(updates)
    d2 = d2 + jnp.eye(m) * 1e30  # exclude self
    k = max(1, m - byzantine_num - 2)
    neg_smallest, _ = jax.lax.top_k(-d2, k)  # (m, k) smallest distances
    return -jnp.sum(neg_smallest, axis=1)


class KrumDefense(Defense):
    """Krum (krum_param_m=1) / Multi-Krum (m>1): keep only the m most central
    clients (zero the rest's weights)."""

    name = "krum"

    def __init__(self, cfg=None, byzantine_num: int = 1, select_m: int = 1):
        super().__init__(cfg)
        self.byzantine_num = getattr(cfg, "byzantine_client_num", byzantine_num) if cfg else byzantine_num
        self.select_m = getattr(cfg, "krum_param_m", select_m) if cfg else select_m

    def before(self, updates, weights, global_flat):
        scores = krum_scores(updates, self.byzantine_num)
        m = updates.shape[0]
        k = min(self.select_m, m)
        _, best = jax.lax.top_k(-scores, k)
        mask = jnp.zeros((m,)).at[best].set(1.0)
        return updates, weights * mask


class MultiKrumDefense(KrumDefense):
    name = "multikrum"


class GeometricMedianDefense(Defense):
    """RFA (Pillutla et al.): smoothed Weiszfeld geometric median of client
    updates, weighted by sample counts.  Fixed ``iters`` under scan."""

    name = "geometric_median"

    def __init__(self, cfg=None, iters: int = 8, eps: float = 1e-6):
        super().__init__(cfg)
        self.iters = iters
        self.eps = eps

    def on_agg(self, updates, weights, global_flat):
        w = weights / jnp.maximum(weights.sum(), 1e-12)
        z0 = w @ updates

        def step(z, _):
            dist = jnp.sqrt(jnp.sum((updates - z[None, :]) ** 2, axis=1) + self.eps)
            alpha = w / dist
            alpha = alpha / jnp.maximum(alpha.sum(), 1e-12)
            return alpha @ updates, None

        z, _ = jax.lax.scan(step, z0, None, length=self.iters)
        return z


class CoordinateWiseMedianDefense(Defense):
    name = "coordinate_median"

    def on_agg(self, updates, weights, global_flat):
        return jnp.median(updates, axis=0)


class TrimmedMeanDefense(Defense):
    """Coordinate-wise beta-trimmed mean: drop the beta*m largest and smallest
    per coordinate, average the rest."""

    name = "trimmed_mean"

    def __init__(self, cfg=None, beta: float = 0.1):
        super().__init__(cfg)
        self.beta = getattr(cfg, "trimmed_mean_beta", beta) if cfg else beta

    def on_agg(self, updates, weights, global_flat):
        m = updates.shape[0]
        b = min(int(self.beta * m), (m - 1) // 2)
        if b == 0:
            return jnp.mean(updates, axis=0)
        s = jnp.sort(updates, axis=0)
        return jnp.mean(s[b : m - b], axis=0)


class BulyanDefense(Defense):
    """Bulyan: MultiKrum-select 2f+3... simplified faithful variant — select
    theta = m - 2f clients by Krum score, then coordinate-wise trimmed mean
    (trim f) over the selected set, implemented with weight masking to keep
    shapes static."""

    name = "bulyan"

    def __init__(self, cfg=None, byzantine_num: int = 1):
        super().__init__(cfg)
        self.byzantine_num = getattr(cfg, "byzantine_client_num", byzantine_num) if cfg else byzantine_num

    def on_agg(self, updates, weights, global_flat):
        m, d = updates.shape
        f = self.byzantine_num
        theta = max(1, m - 2 * f)
        scores = krum_scores(updates, f)
        _, best = jax.lax.top_k(-scores, theta)
        sel = updates[best]  # (theta, d)
        b = min(f, (theta - 1) // 2)
        if b == 0:
            return jnp.mean(sel, axis=0)
        s = jnp.sort(sel, axis=0)
        return jnp.mean(s[b : theta - b], axis=0)
