"""TrustPipeline — attacks, defenses, and DP around aggregation.

This is the TPU form of the reference's lifecycle-hook chain (SURVEY.md §2.2):
``ClientTrainer.on_after_local_training`` (LDP noise) ->
``ServerAggregator.on_before_aggregation`` (defense filter + attack sim) ->
``agg`` (defense may replace the operator) ->
``on_after_aggregation`` (CDP clip/noise, defense post-processing)
(``core/alg_frame/client_trainer.py:61-97``, ``server_aggregator.py:44-104``).

All three hooks are pure and traced into the round program.  They operate on
the flat (m, d) matrix of stacked client contributions; structured
contributions (SCAFFOLD tuples etc.) are flattened wholesale — attack/defense
geometry is calibrated for weights-style contributions, matching the
reference, which likewise applies defenses to the raw client state_dict list.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core import pytree as pt
from .attack.attacks import FedMLAttacker
from .defense import create as create_defense
from .dp.dp import FedMLDifferentialPrivacy


class TrustPipeline:
    def __init__(self, cfg):
        self.cfg = cfg
        self.attacker = FedMLAttacker(cfg) if getattr(cfg, "enable_attack", False) else None
        self.defense = create_defense(cfg) if getattr(cfg, "enable_defense", False) else None
        self.dp = FedMLDifferentialPrivacy(cfg) if getattr(cfg, "enable_dp", False) else None

    @property
    def active(self) -> bool:
        return any((self.attacker, self.defense, self.dp))

    @property
    def needs_history(self) -> bool:
        """True when the defense consumes the previous round's global delta
        (cross-round family); the engine then threads it as a round argument."""
        return self.defense is not None and hasattr(self.defense, "set_history")

    def supports_streaming(self) -> bool:
        """True when the pipeline never needs the STACKED per-client matrix
        — attacks and defenses inspect/transform individual contributions,
        and LDP noises each client's update, so any of them forces the
        buffer-all path; central DP only touches the finalized aggregate
        (hook 3), which the streaming fold applies once at finalize
        (ISSUE 15).  The cross-silo servers consult this to keep trust on
        the associative fast path instead of forcing exact mode."""
        return (self.attacker is None and self.defense is None
                and (self.dp is None or not self.dp.is_ldp_enabled()))

    # -- hook 1: on client outputs (attack simulation + LDP) -----------------
    def on_client_outputs(self, contribs, weights, sampled_idx, global_vars, key):
        run_attack = self.attacker is not None and self.attacker.is_model_attack()
        run_ldp = self.dp is not None and self.dp.is_ldp_enabled()
        if not run_attack and not run_ldp:
            return contribs, weights
        mat = pt.stacked_tree_to_matrix(contribs)
        gflat = self._reference_flat(contribs, global_vars, mat.shape[1])
        if run_attack:
            mat = self.attacker.poison_model(mat, sampled_idx, gflat, jax.random.fold_in(key, 0xA77))
        if run_ldp:
            keys = jax.random.split(jax.random.fold_in(key, 0x1D9), mat.shape[0])
            mat = jax.vmap(self.dp.add_local_noise)(mat, keys)
        return pt.matrix_to_stacked_tree(mat, contribs), weights

    # -- hook 2: before/at aggregation (defenses) ----------------------------
    def on_aggregation(self, contribs, weights, global_vars, key, prev_delta=None):
        """Returns (contribs, weights, agg_override_tree_or_None)."""
        if self.defense is None:
            return contribs, weights, None
        if hasattr(self.defense, "set_key"):
            self.defense.set_key(jax.random.fold_in(key, 0xDEF))
        if prev_delta is not None and hasattr(self.defense, "set_history"):
            self.defense.set_history(prev_delta)
        mat = pt.stacked_tree_to_matrix(contribs)
        gflat = self._reference_flat(contribs, global_vars, mat.shape[1])
        mat, weights = self.defense.before(mat, weights, gflat)
        agg_flat = self.defense.on_agg(mat, weights, gflat)
        contribs = pt.matrix_to_stacked_tree(mat, contribs)
        agg_tree = None
        if agg_flat is not None:
            one = jax.tree_util.tree_map(lambda x: x[0], contribs)
            _, unravel = pt.tree_flatten_to_vector(one)
            agg_tree = unravel(agg_flat)
        return contribs, weights, agg_tree

    # -- hook 3: after aggregation (CDP + defense post) ----------------------
    def on_after_aggregation(self, new_global_vars, old_global_vars, key):
        touched = False
        flat, unravel = pt.tree_flatten_to_vector(new_global_vars)
        old_flat, _ = pt.tree_flatten_to_vector(old_global_vars)
        if self.dp is not None and self.dp.is_cdp_enabled():
            delta = self.dp.global_clip(flat - old_flat)
            flat = old_flat + delta
            flat = self.dp.add_global_noise(flat, jax.random.fold_in(key, 0xCD9))
            touched = True
        if self.defense is not None:
            new_flat = self.defense.after(flat, old_flat)
            touched = touched or (new_flat is not flat)
            flat = new_flat
        return unravel(flat) if touched else new_global_vars

    @staticmethod
    def _reference_flat(contribs, global_vars, d):
        """Flat global reference matching the contribution structure, or zeros
        when contributions aren't weight-shaped (e.g. gradient contributions)."""
        one = jax.tree_util.tree_map(lambda x: x[0], contribs)
        if jax.tree_util.tree_structure(one) == jax.tree_util.tree_structure(global_vars):
            flat, _ = pt.tree_flatten_to_vector(global_vars)
            if flat.shape[0] == d:
                return flat
        return jnp.zeros((d,), jnp.float32)


def build_trust_pipeline(cfg) -> Optional[TrustPipeline]:
    tp = TrustPipeline(cfg)
    return tp if tp.active else None
