"""Differential privacy: mechanisms, LDP/CDP solutions, NbAFL.

Parity with ``core/dp/`` (``FedMLDifferentialPrivacy``
``fedml_differential_privacy.py:13``; mechanisms ``mechanisms/gaussian.py``,
``laplace.py``; frames ``frames/NbAFL.py``, ``cdp.py``, ``ldp.py``).

- LDP: noise added to each client's update before it leaves the client
  (hook: after local training).
- CDP: clip client deltas + noise the aggregated global (hook: after
  aggregation).
- NbAFL: both up-link and down-link noise with the paper's sigma formulas.

All pure: noise keys flow from the round key; calibration is the standard
(epsilon, delta)-Gaussian / epsilon-Laplace mechanism math.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def gaussian_sigma(epsilon: float, delta: float, sensitivity: float) -> float:
    """Classic analytic Gaussian mechanism calibration
    (mechanisms/gaussian.py): sigma = sqrt(2 ln(1.25/delta)) * S / eps."""
    return math.sqrt(2.0 * math.log(1.25 / delta)) * sensitivity / epsilon


def laplace_scale(epsilon: float, sensitivity: float) -> float:
    return sensitivity / epsilon


def add_gaussian_noise(x: jax.Array, key: jax.Array, sigma: float) -> jax.Array:
    return x + jax.random.normal(key, x.shape) * sigma


def add_laplace_noise(x: jax.Array, key: jax.Array, scale: float) -> jax.Array:
    return x + jax.random.laplace(key, x.shape) * scale


def clip_by_norm(x: jax.Array, clip: float) -> jax.Array:
    n = jnp.linalg.norm(x)
    return x * jnp.minimum(1.0, clip / jnp.maximum(n, 1e-12))


class FedMLDifferentialPrivacy:
    """Facade with the reference's API shape (is_ldp_enabled/is_cdp_enabled/
    add_local_noise/add_global_noise + clipping)."""

    def __init__(self, cfg):
        self.enabled = bool(getattr(cfg, "enable_dp", False))
        self.solution = getattr(cfg, "dp_solution_type", "ldp").lower()
        self.mechanism = getattr(cfg, "mechanism_type", "gaussian").lower()
        self.epsilon = float(getattr(cfg, "epsilon", 1.0))
        self.delta = float(getattr(cfg, "delta", 1e-5))
        self.sensitivity = float(getattr(cfg, "sensitivity", 1.0))
        self.clipping_norm = float(getattr(cfg, "clipping_norm", 1.0))

    def is_ldp_enabled(self) -> bool:
        return self.enabled and self.solution in ("ldp", "nbafl")

    def is_cdp_enabled(self) -> bool:
        return self.enabled and self.solution in ("cdp", "nbafl")

    def _noise(self, x, key):
        if self.mechanism == "gaussian":
            return add_gaussian_noise(x, key, gaussian_sigma(self.epsilon, self.delta, self.sensitivity))
        if self.mechanism == "laplace":
            return add_laplace_noise(x, key, laplace_scale(self.epsilon, self.sensitivity))
        raise ValueError(f"unknown mechanism {self.mechanism!r}")

    def add_local_noise(self, update_flat: jax.Array, key: jax.Array) -> jax.Array:
        """LDP: per-client noise on the update (reference ldp.py)."""
        return self._noise(update_flat, key)

    def add_global_noise(self, global_flat: jax.Array, key: jax.Array) -> jax.Array:
        """CDP: noise on the aggregate (reference cdp.py / NbAFL down-link)."""
        return self._noise(global_flat, key)

    def global_clip(self, delta_flat: jax.Array) -> jax.Array:
        return clip_by_norm(delta_flat, self.clipping_norm)


def nbafl_uplink_sigma(clip: float, n_local: int, epsilon: float, delta: float) -> float:
    """NbAFL (Wei et al., frames/NbAFL.py) up-link sigma_u = c*C*L/(n*eps)
    with c = sqrt(2 ln(1.25/delta)); L=1 exposure per round."""
    c = math.sqrt(2.0 * math.log(1.25 / delta))
    return c * clip / max(n_local, 1) / epsilon


def nbafl_downlink_sigma(clip: float, n_clients: int, rounds: int, epsilon: float, delta: float) -> float:
    """NbAFL down-link sigma_d; zero when rounds <= sqrt(N) (paper Thm 2)."""
    if rounds <= math.sqrt(n_clients):
        return 0.0
    c = math.sqrt(2.0 * math.log(1.25 / delta))
    return 2.0 * c * clip * math.sqrt(rounds**2 - n_clients) / (max(n_clients, 1) * epsilon)
