"""RDP budget accountant.

Parity with ``core/dp/budget_accountant/rdp_accountant.py`` (the standard
moments-accountant math from Abadi et al. / Mironov): compute Renyi-DP of the
subsampled Gaussian mechanism at a grid of orders, compose across rounds, and
convert to (epsilon, delta)-DP.  Pure numpy (host-side bookkeeping).
"""

from __future__ import annotations

import math

import numpy as np

DEFAULT_ORDERS = tuple([1.25, 1.5, 1.75, 2.0, 2.5, 3.0, 4.0, 5.0, 6.0, 8.0, 16.0, 32.0, 64.0] + list(range(2, 64)))


def _log_add(a: float, b: float) -> float:
    if a == -np.inf:
        return b
    if b == -np.inf:
        return a
    return max(a, b) + math.log1p(math.exp(-abs(a - b)))


def _compute_log_a_int(q: float, sigma: float, alpha: int) -> float:
    """RDP of subsampled Gaussian for integer alpha (binomial expansion)."""
    log_a = -np.inf
    for i in range(alpha + 1):
        log_coef = (
            math.lgamma(alpha + 1) - math.lgamma(i + 1) - math.lgamma(alpha - i + 1)
            + i * math.log(q) + (alpha - i) * math.log(1 - q)
        )
        log_term = log_coef + (i * i - i) / (2.0 * sigma**2)
        log_a = _log_add(log_a, log_term)
    return log_a


def compute_rdp(q: float, noise_multiplier: float, steps: int, orders=DEFAULT_ORDERS) -> np.ndarray:
    """RDP epsilon at each order for `steps` compositions of the subsampled
    Gaussian with sampling rate q and noise multiplier sigma."""
    rdp = []
    for a in orders:
        if q == 0:
            rdp.append(0.0)
        elif q == 1.0:
            rdp.append(a / (2.0 * noise_multiplier**2))
        elif float(a).is_integer():
            rdp.append(_compute_log_a_int(q, noise_multiplier, int(a)) / (a - 1))
        else:
            # fractional orders: conservative bound via floor/ceil interpolation
            lo = _compute_log_a_int(q, noise_multiplier, int(math.floor(a)))
            hi = _compute_log_a_int(q, noise_multiplier, int(math.ceil(a)))
            rdp.append(max(lo, hi) / (a - 1))
    return np.array(rdp) * steps


def get_privacy_spent(orders, rdp: np.ndarray, delta: float) -> tuple[float, float]:
    """Convert composed RDP to (epsilon, best_order) at target delta."""
    orders = np.asarray(orders, dtype=float)
    eps = rdp - math.log(delta) / (orders - 1)
    idx = int(np.argmin(eps))
    return float(eps[idx]), float(orders[idx])


class RDPAccountant:
    """Stateful accountant (reference class shape): ``step()`` per round,
    ``get_epsilon(delta)`` any time."""

    def __init__(self, q: float, noise_multiplier: float, orders=DEFAULT_ORDERS):
        self.q = q
        self.noise_multiplier = noise_multiplier
        self.orders = orders
        self.steps = 0

    def step(self, n: int = 1) -> None:
        self.steps += n

    def get_epsilon(self, delta: float) -> float:
        if self.steps == 0:
            return 0.0
        rdp = compute_rdp(self.q, self.noise_multiplier, self.steps, self.orders)
        eps, _ = get_privacy_spent(self.orders, rdp, delta)
        return eps
