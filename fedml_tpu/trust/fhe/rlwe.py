"""Additively-homomorphic RLWE encryption for federated aggregation.

Capability parity with ``core/fhe/fhe_agg.py:10`` (reference: TenSEAL CKKS
vectors, shared context, ciphertext addition on the server).  The build image
has no FHE library, so this is a self-contained BFV-style scheme over
R_q = Z_q[x]/(x^N + 1):

    keygen:   s <- {-1, 0, 1}^N (ternary secret)
    encrypt:  a <- U(Z_q^N);  e <- small noise
              ct = (c0, c1) = (-(a*s) + e + delta * m,  a)      delta = q // t
    add:      component-wise mod q  (noise adds linearly)
    scale:    integer plaintext scalar w: (w*c0, w*c1)  (noise grows by w)
    decrypt:  m = round_t((c0 + c1 * s mod q, centered) / delta)

Fixed-point encoding mirrors the SecAgg quantizer (field.py): floats scale by
2^frac_bits into Z_t, negatives wrap.  Exact integer arithmetic uses numpy
object arrays (coefficients reach q^2*N ~ 2^110 during convolution); wire
form is int64 (q < 2^62).  This is deliberately additive-only — FedAvg
aggregation needs nothing else, and avoiding relinearization keeps the
implementation auditable.

Threat model (same as the reference's shared-context design): every client
holds the context (with secret key); the SERVER aggregates ciphertexts and
only ever decrypts the aggregate, never an individual update.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

import numpy as np


@dataclass(frozen=True)
class RLWEParams:
    n: int = 1024               # ring dimension (power of two)
    q: int = 1 << 50            # ciphertext modulus
    t: int = 1 << 30            # plaintext modulus
    noise_bound: int = 4        # uniform noise in [-b, b]
    frac_bits: int = 16         # fixed-point fraction bits

    @property
    def delta(self) -> int:
        return self.q // self.t


def _poly_mul_negacyclic(a: np.ndarray, b: np.ndarray, q: int) -> np.ndarray:
    """Exact (a * b) mod (x^N + 1, q) via object-int convolution."""
    n = len(a)
    full = np.convolve(a.astype(object), b.astype(object))
    out = full[:n].copy()
    out[: len(full) - n] -= full[n:]  # x^N = -1
    return np.mod(out, q)


def keygen(params: RLWEParams, rng: np.random.RandomState) -> np.ndarray:
    return rng.randint(-1, 2, size=params.n).astype(object)


@dataclass
class Ciphertext:
    c0: np.ndarray  # object ints mod q
    c1: np.ndarray

    def to_int64(self) -> np.ndarray:
        """(2, N) int64 wire form (q < 2^62)."""
        return np.stack([self.c0.astype(np.int64), self.c1.astype(np.int64)])

    @classmethod
    def from_int64(cls, arr: np.ndarray) -> "Ciphertext":
        return cls(arr[0].astype(object), arr[1].astype(object))


class RLWECipher:
    """Shared-context cipher: everyone constructing with the same seed holds
    the same secret key (the reference ships a pickled TenSEAL context the
    same way)."""

    def __init__(self, params: RLWEParams = RLWEParams(), key_seed: int = 0):
        self.params = params
        self._s = keygen(params, np.random.RandomState(np.random.SeedSequence(key_seed).generate_state(8)))
        # encryption randomness must NOT be shared — fresh OS entropy
        self._rng = np.random.RandomState(np.random.SeedSequence().generate_state(8))

    # -- fixed-point codec ---------------------------------------------------
    def encode(self, x: np.ndarray) -> np.ndarray:
        p = self.params
        q = np.round(np.asarray(x, dtype=np.float64) * (1 << p.frac_bits)).astype(object)
        return np.mod(q, p.t)

    def decode(self, m: np.ndarray) -> np.ndarray:
        p = self.params
        m = np.mod(m.astype(object), p.t)
        half = p.t // 2
        signed = np.where(m > half, m - p.t, m)
        return signed.astype(np.float64) / (1 << p.frac_bits)

    # -- core ops ------------------------------------------------------------
    def encrypt_poly(self, m: np.ndarray) -> Ciphertext:
        p = self.params
        a = self._rng.randint(0, 1 << 62, size=p.n).astype(object) % p.q
        e = self._rng.randint(-p.noise_bound, p.noise_bound + 1, size=p.n).astype(object)
        c0 = np.mod(-_poly_mul_negacyclic(a, self._s, p.q) + e + p.delta * m, p.q)
        return Ciphertext(c0, a)

    def decrypt_poly(self, ct: Ciphertext) -> np.ndarray:
        p = self.params
        raw = np.mod(ct.c0 + _poly_mul_negacyclic(ct.c1, self._s, p.q), p.q)
        centered = np.where(raw > p.q // 2, raw - p.q, raw)
        # exact rounding division on object ints (float64 loses bits at 2^50)
        d = p.delta
        m = np.array([(int(v) + d // 2) // d for v in centered], dtype=object)
        return np.mod(m, p.t)

    # -- vector API (the fhe_enc/fhe_dec shape of the reference) -------------
    def encrypt_vector(self, x: np.ndarray) -> List[np.ndarray]:
        """float vector -> list of (2, N) int64 ciphertext blocks."""
        p = self.params
        m = self.encode(x)
        pad = (-len(m)) % p.n
        m = np.concatenate([m, np.zeros(pad, dtype=object)])
        return [
            self.encrypt_poly(m[i : i + p.n]).to_int64()
            for i in range(0, len(m), p.n)
        ]

    def decrypt_vector(self, blocks: List[np.ndarray], length: int) -> np.ndarray:
        out = np.concatenate([self.decrypt_poly(Ciphertext.from_int64(b)) for b in blocks])
        return self.decode(out[:length])


def add_ciphertexts(blocks_list: List[List[np.ndarray]], q: int) -> List[np.ndarray]:
    """Server-side: component-wise sum of clients' ciphertext block lists —
    the only operation the aggregator performs (no key needed)."""
    n_blocks = len(blocks_list[0])
    out = []
    for b in range(n_blocks):
        acc = np.zeros_like(blocks_list[0][b], dtype=object)
        for blocks in blocks_list:
            acc = acc + blocks[b].astype(object)
        out.append(np.mod(acc, q).astype(np.int64))
    return out


def scale_ciphertext(blocks: List[np.ndarray], w: int, q: int) -> List[np.ndarray]:
    """Integer plaintext scalar multiply (for integer-weighted aggregation)."""
    return [np.mod(b.astype(object) * int(w), q).astype(np.int64) for b in blocks]
